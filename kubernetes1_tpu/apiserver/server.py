"""HTTP API server: REST + streaming watch over the registry.

Ref: cmd/kube-apiserver + staging/src/k8s.io/apiserver/pkg/server — the
filter chain (authn -> audit -> authz -> admission) collapses here to a
bearer-token check hook, an audit log hook, and the admission chain; the
wire protocol is the reference's: JSON objects, list kinds with a
resourceVersion for watch resume, and watch streams as line-delimited
{"type","object"} frames over chunked HTTP (exactly what client-go's
reflector consumes).

The in-process `Master` is the master_utils.RunAMaster equivalent
(test/integration/framework/master_utils.go:193): tests and the local
cluster boot embed a full apiserver over the MVCC store with zero setup.
"""

from __future__ import annotations

import bisect
import json
import os
import socket
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..utils import (
    eventloop as _eventloop,
    fasthttp,
    faultline,
    flightrec,
    locksan,
    schedsan,
    spans as spanlib,
)
from urllib.parse import parse_qs, urlparse

from ..api import types as t
from ..obs import appmetrics
from ..machinery import (
    ApiError,
    BadRequest,
    ERROR,
    Forbidden,
    NotFound,
    TooOldResourceVersion,
    Unauthorized,
)
from ..machinery.errors import TooManyRequests
from ..machinery.scheme import Scheme, global_scheme
from ..storage import (
    CacheNotReady,
    Cacher,
    DEFAULT_WATCH_QUEUE_LIMIT,
    ShardedCacher,
    ShardedStore,
    Store,
    build_sharded_store,
    parse_rv,
    parse_shard_addresses,
)
from .admission import (
    CREATE,
    UPDATE,
    AdmissionChain,
    AlwaysPullImages,
    DefaultTolerationSeconds,
    EventRateLimit,
    ExtendedResourceToleration,
    GangDefaulter,
    IdentityStamp,
    LimitRanger,
    MutatingWebhookAdmission,
    NamespaceAutoProvision,
    NodeRestriction,
    PodNodeSelector,
    PodPresetAdmission,
    PodSecurityPolicyAdmission,
    PriorityResolver,
    ResourceQuotaAdmission,
    ResourceV2,
    ServiceAccountAdmission,
    ValidatingWebhookAdmission,
    compute_namespace_usage,
)
from .auth import (
    ANONYMOUS,
    GROUP_AUTHENTICATED,
    GROUP_MASTERS,
    AlwaysAllowAuthorizer,
    AuthenticatorChain,
    AuthorizerChain,
    BootstrapTokenAuthenticator,
    CertificateAuthenticator,
    NodeAuthorizer,
    OIDCAuthenticator,
    RBACAuthorizer,
    ServiceAccountAuthenticator,
    StaticTokenAuthenticator,
    UserInfo,
    WebhookTokenAuthenticator,
    verb_for,
)
from .registry import Registry

WATCH_HEARTBEAT_SECONDS = 5.0


def _encode_chunks(frames) -> bytes:
    """Frame N watch payloads as chunked-transfer bytes — ONE buffer, so
    a batch costs one syscall and one client recv wakeup.  The chunked-
    encoding wire format lives only here, shared by the threaded serving
    loop and the event-loop dispatcher: two serving modes, one set of
    wire bytes (the golden parity test pins this)."""
    buf = bytearray()
    for data in frames:
        if not data:
            # zero-length would terminate chunked encoding; a newline
            # keeps the stream alive (heartbeats ride this)
            data = b"\n"
        buf += b"%x\r\n" % len(data) + data + b"\r\n"
    return bytes(buf)


class _WatchStream:
    """Per-watch frame factory: everything about one watch stream's wire
    frames (event frames, composite/progress/lag BOOKMARK frames, the
    410-eviction frame) with no I/O.  Both serving modes — the threaded
    loop parked in ``_serve_watch`` and the event-loop ``_WatchConn``
    state machine — build their bytes HERE, so the wire cannot drift
    between them."""

    def __init__(self, master: "Master", w, q: Dict[str, str], ver: str):
        self.master = master
        self.w = w
        self.ver = ver
        # merged multi-shard streams interleave shards (cross-shard order
        # is per-shard only), so a single per-object rv cannot encode the
        # stream's position — BOOKMARK frames carrying the composite
        # resume position do (the Kubernetes watch-bookmark analog).
        # Plain streams never emit them: byte-identical wire at shards=1.
        self.bookmarks = getattr(w, "emit_bookmarks", False)
        # watch-lag SLI opt-in (?lagStamps=1, informers set it): after
        # every delivered batch, a BOOKMARK frame carries the monotonic
        # commit stamp of the batch's newest revision PER SHARD
        # (obs.ktpu.io/committed-at, "<shard>:<ts>" tokens) so the
        # client can export delivered-at minus committed-at without any
        # cross-shard clock math.  Streams that didn't ask stay
        # byte-identical — stamps never ride the cached event frames.
        self.lag_stamps = q.get("lagStamps") in ("1", "true")
        # progress-bookmark opt-in (?progressBookmarks=1, informers set
        # it): PLAIN streams (shards=1, no composite bookmarks) get a
        # BOOKMARK frame on idle heartbeats carrying a SAFE resume
        # revision (Watcher.progress_rv — the cache head, but only when
        # nothing is queued undelivered), so an informer idle for minutes
        # resumes above the compaction floor instead of 410-full-
        # relisting the collection.  Streams that didn't ask stay
        # byte-identical; merged streams already bookmark every
        # heartbeat.
        self.progress = (not self.bookmarks
                         and q.get("progressBookmarks") in ("1", "true"))
        self.n_shards = max(1, master.store_shards)

    def bookmark_frame(self) -> bytes:
        self.master.note_watch_bookmark()
        return (b'{"type":"BOOKMARK","object":{"kind":"Bookmark",'
                b'"apiVersion":"v1","metadata":{"resourceVersion":"'
                + self.w.bookmark_rv().encode() + b'"}}}\n')

    def progress_frame(self) -> Optional[bytes]:
        fn = getattr(self.w, "progress_rv", None)
        rv = fn() if fn is not None else None
        if not rv:
            return None  # unsafe this tick (events in flight): skip
        self.master.note_watch_bookmark()
        return (b'{"type":"BOOKMARK","object":{"kind":"Bookmark",'
                b'"apiVersion":"v1","metadata":{"resourceVersion":"'
                + str(rv).encode() + b'"}}}\n')

    def lag_frame(self, evs) -> Optional[bytes]:
        """Lag-stamp bookmark for one delivered batch (None when no
        stamp is available and the stream has no bookmark position
        to refresh either)."""
        per_shard: Dict[int, int] = {}
        for ev in evs:
            try:
                rev = int((ev.object.get("metadata") or {})
                          .get("resourceVersion") or 0)
            except (TypeError, ValueError, AttributeError):
                continue
            if rev > per_shard.get(rev % self.n_shards, 0):
                per_shard[rev % self.n_shards] = rev
        toks = []
        for sh in sorted(per_shard):
            ts = self.master.store.commit_ts_of(per_shard[sh])
            if ts is not None:
                toks.append(f"{sh}:{ts:.6f}")
        if not toks and not self.bookmarks:
            return None
        rv = (self.w.bookmark_rv() if self.bookmarks
              else str(max(per_shard.values(), default=0)))
        meta: Dict[str, Any] = {"resourceVersion": rv}
        if toks:
            meta["annotations"] = {
                t.COMMITTED_AT_ANNOTATION: " ".join(toks)}
        self.master.note_watch_bookmark()
        return json.dumps(
            {"type": "BOOKMARK",
             "object": {"kind": "Bookmark", "apiVersion": "v1",
                        "metadata": meta}},
            separators=(",", ":")).encode() + b"\n"

    def heartbeat_frame(self) -> bytes:
        """The idle-tick frame: a composite bookmark on merged streams, a
        progress bookmark when opted in and safe, else the empty payload
        (an encoder-level ``\\n`` keep-alive chunk)."""
        fr = (self.bookmark_frame() if self.bookmarks
              else self.progress_frame() if self.progress else None)
        return fr if fr else b""

    def batch_frames(self, evs) -> List[bytes]:
        """One delivered batch -> its wire frames.  WatchEvents are
        SHARED by every watcher of the resource (one fan-out wakeup per
        group commit) and the payload bytes come from the scheme's
        once-per-revision serialization cache — N watchers plus every
        list/get of the same revision cost ONE encode (the reference's
        cacher economics, storage/cacher.go)."""
        frames = [self.master.scheme.watch_frame_bytes(
                      ev.type, ev.object, self.ver)
                  for ev in evs if self.w.event_matches(ev.object)]
        if self.bookmarks or self.lag_stamps:
            # after every delivered batch: the bookmark rides the
            # same buffered write, so a cut can strand at most
            # one batch's worth of single-int rv — and the
            # informer resumes from the last composite it holds
            # (duplicates are idempotent; gaps would be lost
            # state).  Selector-filtered batches still bookmark:
            # the position advanced even if no frame matched.
            # With lagStamps the commit stamp rides the same
            # bookmark frame; without it the handcrafted bytes
            # stay exactly what PR 10 shipped.
            fr = (self.lag_frame(evs) if self.lag_stamps
                  else self.bookmark_frame())
            if fr is not None:
                frames.append(fr)
        return frames

    def eviction_frame(self) -> bytes:
        """The 410 ERROR frame a slow/stale consumer's stream ends with
        (the reference cacher's eviction contract, storage/cacher.go)."""
        status = TooOldResourceVersion(
            "watch evicted; relist required").to_status()
        return json.dumps({"type": ERROR, "object": status},
                          separators=(",", ":")).encode() + b"\n"


# selectors event masks, local names for the conn state machine
_EV_READ = 1   # selectors.EVENT_READ
_EV_WRITE = 2  # selectors.EVENT_WRITE


class _WatchConn:
    """One handed-off watch connection's state machine on the shared
    dispatcher: the event-loop replacement for a ThreadingHTTPServer
    thread parked in ``_serve_watch``'s blocking loop.

    State: the socket (detached from the HTTP server after the chunked
    headers went out), the per-connection cacher batch cursor (the
    Watcher, drained with ``next_batch_nowait`` on its notify hook), a
    bounded outbuf of pending wire bytes, and heartbeat/deadline timers
    on the loop.

    Semantics carried over from the threaded loop unchanged:

    - BACKPRESSURE: the watcher is drained ONLY while the outbuf is
      empty.  A client that stops reading leaves bytes in the outbuf, the
      drain stops, the watcher's bounded queue fills, and the existing
      slow-consumer eviction fires — exactly what a blocked sendall
      produced, with per-connection memory bounded by one batch's frames
      instead of a whole thread stack.
    - HEARTBEATS: a per-connection loop timer re-armed on every delivered
      batch emits the same idle-tick frame (composite/progress bookmark
      or keep-alive chunk) at the same cadence.
    - 410 EVICTION: stream end with ``evicted`` set writes the ERROR
      frame, then the terminal chunk — byte-identical to the threaded
      path.
    - TEARDOWN: peer hangup (zero-byte read) or a write error stops the
      watcher and closes; server stop ends every stream with a terminal
      chunk, like the threaded loop's ``stopping`` check.

    All methods run on the loop thread; the watcher notify hook crosses
    threads via ``call_soon``.  The flush point is a faultline site
    (``watch.flush``) — chaos severs frames mid-write and schedsan gets
    a preemption point — and the handoff is a schedsan site
    (``apiserver.watch.handoff``)."""

    def __init__(self, master: "Master", stream: _WatchStream, sock,
                 deadline: Optional[float]):
        self.master = master
        self.stream = stream
        self.w = stream.w
        self.sock = sock
        self.deadline = deadline
        self.loop = master.dispatcher()
        self.outbuf = bytearray()
        self.closed = False
        self.finishing = False  # terminal chunk queued; close after flush
        self._events = _EV_READ  # current selector interest
        self._pump_pending = False
        self._registered = False
        self._hb_timer = None
        self._deadline_timer = None

    # ----------------------------------------------------------- lifecycle

    def start(self):
        """Loop thread: register the socket, arm timers, drain anything
        the watcher queued between handoff and registration."""
        try:
            self.sock.setblocking(False)
            self.loop.register(self.sock, _EV_READ, self._on_io)
        except (OSError, ValueError):
            self._teardown()
            return
        self.loop.add_connection()
        self._registered = True
        if self.deadline is not None:
            self._deadline_timer = self.loop.call_later(
                max(0.0, self.deadline - time.monotonic()), self._on_deadline)
        self._reset_heartbeat()
        # notify crosses threads through the loop's self-pipe; installing
        # it fires once, covering events queued before the handoff
        self.w.set_notify(self._notify)

    def _notify(self):
        # any thread, possibly under the cacher's commit lock: must not
        # block.  The pending flag dedups a burst of notifies into one
        # scheduled pump (a stale-flag race costs one no-op pump).
        if self._pump_pending:
            return
        self._pump_pending = True
        self.loop.call_soon(self._pump)

    # --------------------------------------------------------------- I/O

    def _on_io(self, mask: int):
        if self.closed:
            return
        if mask & _EV_READ:
            # a watch client never sends frames; readable means hangup
            # (zero-byte read) or stray bytes we ignore — the threaded
            # handler never read mid-watch either
            try:
                data = self.sock.recv(65536)  # ktpulint: ignore[KTPU016] socket is setblocking(False); recv returns or raises BlockingIOError, never stalls the loop
            except (BlockingIOError, InterruptedError):
                data = b"ignored"
            except OSError:
                self._teardown()
                return
            if not data:
                self._teardown()  # peer closed: same as BrokenPipeError
                return
        if mask & _EV_WRITE:
            self._try_flush()

    def _set_events(self, events: int):
        if events == self._events or self.closed:
            return
        try:
            self.loop.modify(self.sock, events, self._on_io)
            self._events = events
        except (OSError, ValueError, KeyError):
            self._teardown()

    def _send_frames(self, frames: List[bytes]):
        """Chunk-encode and ship through the watch.flush faultline site:
        an injected sever puts the torn prefix on the wire, then the
        connection dies exactly as if the peer cut it mid-frame."""
        data, exc = faultline.filter_bytes("watch.flush",
                                           _encode_chunks(frames))
        self.outbuf += data
        self._try_flush()
        if exc is not None:
            self._teardown()

    def _try_flush(self):
        """Write-ready-driven flushing (replaces blocking sendall): send
        what the socket accepts, keep the rest buffered with write
        interest armed."""
        if self.closed:
            return
        schedsan.preempt("watch.flush")
        while self.outbuf:
            try:
                n = self.sock.send(bytes(self.outbuf))  # ktpulint: ignore[KTPU016] socket is setblocking(False); a full kernel buffer raises BlockingIOError and we re-arm on writability
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._teardown()
                return
            if n <= 0:
                break
            del self.outbuf[:n]
        if self.outbuf:
            self._set_events(_EV_READ | _EV_WRITE)
            return
        self._set_events(_EV_READ)
        if self.finishing:
            self._teardown()
            return
        # the wire is clear again: schedule a pump for whatever backed up
        # while the outbuf held bytes (scheduled, not inline — an inline
        # call would recurse pump->send->flush->pump through a deep
        # backlog)
        self._notify()

    # -------------------------------------------------------------- pump

    def _pump(self):
        self._pump_pending = False
        if self.closed or self.finishing:
            return
        if self.master.stopping.is_set():
            self._end_stream()
            return
        # drain-until-dry, but ONLY while the wire is clear: the first
        # batch that leaves bytes in the outbuf stops the drain, and the
        # watcher's bounded queue takes the backpressure from there
        while not self.outbuf and not self.closed and not self.finishing:
            evs = self.w.next_batch_nowait()
            if evs is None:
                self._end_stream()
                return
            if not evs:
                return
            frames = self.stream.batch_frames(evs)
            self._reset_heartbeat()
            if frames:
                self._send_frames(frames)

    # ------------------------------------------------------------- timers

    def _reset_heartbeat(self):
        if self._hb_timer is not None:
            self._hb_timer.cancel()
        self._hb_timer = self.loop.call_later(
            WATCH_HEARTBEAT_SECONDS, self._on_heartbeat)

    def _on_heartbeat(self):
        if self.closed or self.finishing:
            return
        if self.master.stopping.is_set():
            self._end_stream()
            return
        if getattr(self.w, "closed", False) or self.w._stopped.is_set():
            # upstream stream died or the watcher was stopped server-side
            # — _end_stream answers 410 if evicted, else ends cleanly
            self._end_stream()
            return
        self._send_frames([self.stream.heartbeat_frame()])
        if not self.closed:
            self._reset_heartbeat()

    def _on_deadline(self):
        # timeoutSeconds elapsed: end like the threaded loop's deadline
        # break — terminal chunk, no ERROR frame
        if not self.closed and not self.finishing:
            self.finishing = True
            self.w.stop()
            self.outbuf += b"0\r\n\r\n"
            self._try_flush()

    # ----------------------------------------------------------- shutdown

    def _end_stream(self):
        """Orderly stream end (threaded loop's break + finally): the 410
        ERROR frame when evicted, then the terminal chunk, then close
        once the bytes drain."""
        if self.closed or self.finishing:
            return
        frames = []
        if getattr(self.w, "evicted", False):
            # slow consumer (or cache reseed): this stream can no longer
            # be gap-free.  Answer 410 Expired so the reflector relists.
            frames.append(self.stream.eviction_frame())
        self.finishing = True
        self.w.stop()
        self.outbuf += (_encode_chunks(frames) if frames else b"") \
            + b"0\r\n\r\n"
        self._try_flush()

    def shutdown(self):
        """Master.stop(): end the stream now (loop thread)."""
        self._end_stream()

    def _teardown(self):
        if self.closed:
            return
        self.closed = True
        if self._hb_timer is not None:
            self._hb_timer.cancel()
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        self.w.set_notify(None)
        self.w.stop()
        if getattr(self, "_registered", False):
            self.loop.unregister(self.sock)
            self.loop.remove_connection()
        try:
            self.sock.close()
        except OSError:
            pass  # peer already tore the connection down
        self.master._drop_watch_conn(self)


class _ApiHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with request-socket handoff: a request marked
    detached skips shutdown_request (the dispatcher owns the socket's
    lifecycle from the handoff on; socketserver would otherwise SHUT_WR
    and close it the moment the handler thread returns)."""

    def __init__(self, addr, handler_cls):
        super().__init__(addr, handler_cls)
        self._detached = set()
        self._detach_lock = locksan.make_lock("apiserver._detach_lock")

    def detach_request(self, request):
        with self._detach_lock:
            self._detached.add(request)

    def shutdown_request(self, request):
        with self._detach_lock:
            if request in self._detached:
                self._detached.discard(request)
                return
        super().shutdown_request(request)


def _ratio(hits: int, misses: int) -> float:
    total = hits + misses
    return (hits / total) if total else 0.0


def encode_continue(rv: str, last_key: str) -> str:
    """Opaque LIST continue token: the FIRST chunk's resourceVersion (the
    client's watch-resume anchor, carried through every later token
    unchanged) + the last storage key served.  Base64url JSON — opaque to
    clients, versionable by the server."""
    import base64

    return base64.urlsafe_b64encode(json.dumps(
        {"rv": str(rv), "k": last_key},
        separators=(",", ":")).encode()).decode()


def decode_continue(token: str):
    """-> (rv, last_key).  Raises BadRequest on garbage (a corrupt token
    is a client bug; a STALE token is 410, judged elsewhere)."""
    import base64
    import binascii

    try:
        d = json.loads(base64.urlsafe_b64decode(token.encode()))
        return str(d["rv"]), str(d["k"])
    except (ValueError, KeyError, TypeError, binascii.Error) as e:
        raise BadRequest(f"invalid continue token: {e}") from None


class _AdmissionTTLCache:
    """~1s TTL cache for hot admission inputs, generation-stamped: a
    write-through invalidate() bumps the generation so a store scan that
    RACED the write (started before, finished after) cannot re-publish the
    pre-write view.

    HA semantics (deliberate): invalidation is per-apiserver, so in an
    N-apiserver topology a policy write (PodSecurityPolicy, webhook config)
    through peer A leaves peers B..N admitting against the stale set for up
    to the 1s TTL.  This matches upstream, where admission plugins read
    policy through informer caches that lag the watch stream by the same
    order of staleness (and carry no cross-apiserver invalidation either);
    closing the window would cost a store current_revision round-trip on
    every admission-chain cache hit — the pod-create hot path.  Anything
    needing read-your-write policy enforcement must route the subsequent
    requests through the same apiserver that took the policy write."""

    def __init__(self, ttl: float = 1.0):
        self.ttl = ttl
        self._gen = 0
        self._data: Dict[str, tuple] = {}  # key -> (gen, ts, items)

    def get(self, key: str, fetch):
        now = time.monotonic()
        gen = self._gen
        hit = self._data.get(key)
        if hit is not None and hit[0] == gen and now - hit[1] < self.ttl:
            return hit[2]
        items = fetch()
        if self._gen == gen:
            self._data[key] = (gen, now, items)
        return items

    def invalidate(self):
        self._gen += 1
        self._data.clear()


class _WriteCoalescer:
    """Opt-in write-coalescing window for singleton POST/PUT handlers
    (Master(write_coalesce_window=...), seconds; 0 = off, the default).

    When enabled it engages ONLY under burst: the first writer in flight
    passes straight through (an isolated write pays zero added latency);
    a writer that finds another already in flight parks until the current
    window expires, so a create storm's handlers release toward the store
    in lockstep and the store's group commit drains them as one batch
    (one fan-out wakeup, one WAL fsync).  ~1-5ms windows trade that much
    p50 under burst for batch occupancy; the gate sleeps OUTSIDE every
    lock."""

    def __init__(self, window: float):
        self.window = window
        self._lock = locksan.make_lock("Master._coalesce_lock")
        self._inflight = 0
        self._deadline = 0.0
        self.waits = 0  # ktpu_write_coalesce_waits_total

    def __enter__(self):
        if not self.window:
            return self
        delay = 0.0
        with self._lock:
            self._inflight += 1
            if self._inflight > 1:  # burst: another write is in flight
                now = time.monotonic()
                if self._deadline <= now:
                    self._deadline = now + self.window
                delay = self._deadline - now
                self.waits += 1
        if delay > 0:
            time.sleep(delay)
        return self

    def __exit__(self, *exc):
        if self.window:
            with self._lock:
                self._inflight -= 1
        return False


class _InflightLimiter:
    """Max-inflight overload shedding (ref: apiserver/pkg/server/filters/
    maxinflight.go).  Per-verb-class inflight gauges; MUTATING requests
    past the bound are shed with 429 + Retry-After BEFORE authn/admission/
    commit — the commit queue never sees them, so a write storm degrades
    into client backoff instead of queue collapse.  Reads are never shed:
    they're answered off the watch cache at dict-lookup cost, and a
    degraded control plane that can still be OBSERVED is the difference
    between an incident and an outage."""

    MUTATING = frozenset({"POST", "PUT", "PATCH", "DELETE"})

    def __init__(self, max_mutating: int):
        self.max_mutating = max_mutating  # 0 disables shedding
        self._lock = locksan.make_lock("Master._inflight_lock")
        self._inflight = {"mutating": 0, "readonly": 0}
        self.peak_mutating = 0
        self.shed_total = 0
        # refusals since the last successful mutating admit: the gauge
        # itself is capped at the bound, so THIS is the signal that keeps
        # growing with overload depth (see retry_after)
        self._shed_burst = 0

    def _class_of(self, method: str) -> str:
        return "mutating" if method in self.MUTATING else "readonly"

    def acquire(self, method: str) -> bool:
        cls = self._class_of(method)
        with self._lock:
            if (cls == "mutating" and self.max_mutating
                    and self._inflight["mutating"] >= self.max_mutating):
                self.shed_total += 1
                self._shed_burst += 1
                return False
            self._inflight[cls] += 1
            if cls == "mutating":
                self._shed_burst = 0  # admitting again: the burst drained
                if self._inflight["mutating"] > self.peak_mutating:
                    self.peak_mutating = self._inflight["mutating"]
        return True

    def release(self, method: str):
        cls = self._class_of(method)
        with self._lock:
            self._inflight[cls] -= 1

    def inflight(self, cls: str) -> int:
        with self._lock:
            return self._inflight[cls]

    def retry_after(self) -> float:
        """Seconds the shed client should wait — a 0.5s base scaled up
        with the depth of the current shed burst (refusals since the last
        successful admit; the inflight gauge itself is capped at the
        bound, so it can't measure how far past it demand is), capped so
        a burst's retries still land while it drains.  Clients jitter
        UNDER this floor, so even at the cap the herd spreads."""
        with self._lock:
            burst = self._shed_burst
        return max(0.1, min(2.0, 0.5 * (1.0 + burst / 64.0)))


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ktpu-apiserver/0.1"
    # response headers and body go out as separate small writes; with Nagle
    # on, the body write stalls ~40ms behind the client's delayed ACK —
    # TCP_NODELAY is what every real apiserver/gRPC stack runs with
    disable_nagle_algorithm = True
    # fully-buffered response stream: one syscall per response instead of
    # one per write (handle_one_request flushes after each request; the
    # chunked-watch path flushes per frame explicitly) — the HTTP layer,
    # not the registry, is the measured cost center at 1000-node density
    wbufsize = -1

    # quiet request logging; audit hook covers observability
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def setup(self):
        # TLS handshake runs HERE, in the per-connection thread — wrapping
        # the listener with do_handshake_on_connect=False keeps a slow or
        # plaintext client from stalling the accept loop for everyone
        handshake = getattr(self.request, "do_handshake", None)
        if handshake is not None:
            handshake()
        super().setup()

    # ------------------------------------------------------------- plumbing

    @property
    def master(self) -> "Master":
        return self.server.master  # type: ignore[attr-defined]

    def _send_raw_json(self, code: int, raw: bytes,
                       extra_headers: Optional[Dict[str, str]] = None):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send_json(self, code: int, payload: Dict[str, Any]):
        self._send_raw_json(
            code, json.dumps(payload, separators=(",", ":")).encode())

    def _send_obj(self, code: int, obj):
        """Single-object response through the once-per-revision
        serialization cache: the encode this pays (on miss) populates the
        SAME entry every watch fan-out and list touching this
        (uid, resourceVersion) then reuses."""
        self._send_raw_json(code, self.master.scheme.encode_obj_bytes(
            obj, getattr(self, "_req_version", "")))

    def _send_error(self, err: ApiError):
        # any error answered before the handler read the request body
        # (shed, authn, authz, routing) leaves the body bytes in the
        # keep-alive stream, where the NEXT request on the connection
        # parses them as a request line (observed as a bogus 400 by the
        # shed e2e test) — drain before every error response
        self._drain_unread_body()
        retry_after = getattr(err, "retry_after", None)
        # fractional seconds (the ktpu client parses floats; RFC readers
        # round up) — overload sheds ride this header
        self._send_raw_json(
            err.code,
            json.dumps(err.to_status(), separators=(",", ":")).encode(),
            extra_headers=({"Retry-After": f"{retry_after:.3f}"}
                           if retry_after is not None else None))

    # past this, draining a refused request costs more than closing the
    # connection does — the drain exists to keep keep-alive usable, not
    # to make the server swallow arbitrary bytes it already rejected
    MAX_DRAIN_BYTES = 1 << 20

    def _drain_unread_body(self):
        """Consume the request body if no handler has read it yet (see
        _send_error).  _body_consumed is reset per request in _handle —
        the handler instance is reused across keep-alive requests.
        Chunked reads, bounded: an overload shed must stay CHEAP, so an
        oversized rejected body closes the connection instead of being
        read into memory."""
        if getattr(self, "_body_consumed", True):
            return
        self._body_consumed = True
        try:
            length = int(self.headers.get("Content-Length") or 0)
            if length > self.MAX_DRAIN_BYTES:
                self.close_connection = True
                return
            if not length:
                return
            # time-bounded: a client that trickles (or stalls) its body
            # must not pin this handler thread — shedding exists to FREE
            # threads.  On stall, give up and close; the response still
            # goes out (the timeout is restored first).
            old_timeout = self.connection.gettimeout()
            self.connection.settimeout(5.0)
            try:
                while length > 0:
                    chunk = self.rfile.read(min(length, 65536))
                    if not chunk:
                        self.close_connection = True  # client went away
                        break
                    length -= len(chunk)
            except socket.timeout:
                self.close_connection = True  # undrained bytes: no reuse
            finally:
                self.connection.settimeout(old_timeout)
        except (OSError, ValueError):
            pass  # client already gone, or sent a bad length

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        self._body_consumed = True
        if length == 0:
            raise BadRequest("request body required")
        raw = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype.startswith("application/x-ktpu-"):
            # codec-framed body (the bulk-bind hot path ships pybin1):
            # decoded through the same registry as the store wire — the
            # restricted unpickler refuses any pickle referencing a
            # global, so this accepts only plain data, exactly like JSON
            from ..machinery.codec import CodecError, get_codec, known_codecs

            codec_id = ctype[len("application/x-ktpu-"):]
            if codec_id not in known_codecs():
                raise BadRequest(f"unsupported content type {ctype!r}")
            try:
                body = get_codec(codec_id).decode(raw)
            except CodecError as e:
                raise BadRequest(f"invalid {codec_id} body: {e}") from e
            if not isinstance(body, dict):
                raise BadRequest(f"{codec_id} body must decode to an object")
            return body
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON body: {e}") from e

    def _authn(self) -> UserInfo:
        """Resolve the request's user (ref: authn filter, config.go:530).
        Raises Unauthorized for a presented-but-invalid credential."""
        # x509 first: a verified client certificate on the TLS connection IS
        # the identity (CN=user, O=groups; ref authenticator/request/x509) —
        # the handshake already proved possession against the client CA
        x509_user = self._peer_cert_user()
        if x509_user is not None:
            return x509_user
        header = self.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            if self.master.token or self.master.authorization_mode != "AlwaysAllow":
                return ANONYMOUS
            return UserInfo(name="system:admin", groups=[GROUP_MASTERS])
        token = header[len("Bearer "):]
        user = self.master.authenticators.authenticate(token)
        if user is None:
            raise Unauthorized("invalid bearer token")
        return user

    def _peer_cert_user(self) -> Optional[UserInfo]:
        """UserInfo from the verified TLS peer certificate, if any."""
        getpeercert = getattr(self.connection, "getpeercert", None)
        if getpeercert is None:
            return None
        try:
            cert = getpeercert()
        except (ValueError, OSError):
            return None
        if not cert:
            return None  # no client cert presented (token path instead)
        name, orgs = "", []
        for rdn in cert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName":
                    name = value
                elif key == "organizationName":
                    orgs.append(value)
        if not name:
            return None
        return UserInfo(name=name, groups=orgs + [GROUP_AUTHENTICATED])

    def _check_kind(self, resource: str, obj):
        """The body's kind must be the resource's registered kind — the
        Unstructured decode fallback (for dynamic clients) must not let a
        typo'd kind land in a typed registry."""
        scheme = self.master.scheme
        if resource in scheme.dynamic_resources:
            want_kind = scheme.dynamic_resources[resource]
        else:
            want_kind = scheme.by_resource[resource].KIND
        from ..machinery.scheme import Unstructured as _U

        have_kind = obj.kind if isinstance(obj, _U) else type(obj).KIND
        if want_kind and have_kind != want_kind:
            raise BadRequest(
                f"body kind {have_kind!r} does not match resource {resource!r} "
                f"(want {want_kind!r})"
            )
        if isinstance(obj, _U) and resource not in scheme.dynamic_resources:
            raise BadRequest(f"resource {resource!r} requires a typed {want_kind!r} body")

    def _authz(self, user: UserInfo, verb: str, resource: str, ns: str, name: str,
               sub: str = ""):
        if not self.master.authorizer.authorize(user, verb, resource, ns, name, sub=sub):
            raise Forbidden(
                f'user "{user.name}" cannot {verb} {resource}'
                + (f' "{name}"' if name else "")
                + (f' in namespace "{ns}"' if ns else "")
            )

    def _proxy_to_apiservice(self, svc_ref, method: str):
        """Forward the request verbatim to the aggregated API server's
        endpoint (ref: kube-aggregator proxy handler)."""
        import http.client

        addr = self.master.resolve_service_endpoint(
            svc_ref.spec.service_namespace, svc_ref.spec.service_name,
            svc_ref.spec.service_port,
        )
        if addr is None:
            raise ApiError(
                f"no endpoints for aggregated API service "
                f"{svc_ref.metadata.name}"
            )
        host, port = addr
        length = int(self.headers.get("Content-Length") or 0)
        self._body_consumed = True
        body = self.rfile.read(length) if length else None
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            # identity forwarded the way the reference's front-proxy does
            conn.request(method, self.path, body=body,
                         headers={
                             "Content-Type": "application/json",
                             "X-Remote-User": self._user.name,
                             "X-Remote-Group": ",".join(self._user.groups),
                         })
            resp = conn.getresponse()
            raw = resp.read()
            self.send_response(resp.status)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type", "application/json"))
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)
        finally:
            conn.close()

    # ------------------------------------------------------------- dispatch

    def _route(self):
        parsed = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        parts = [p for p in parsed.path.split("/") if p]
        return parts, q

    def _parse_resource_path(self, parts):
        """Return (resource, namespace, name, subresource).

        Accepted forms (group prefixes /api/v1 and /apis/<g>/<v> both map to
        the single flat registry):
          <prefix>/<resource>
          <prefix>/<resource>/<name>[/<sub>]
          <prefix>/namespaces/<ns>/<resource>[/<name>[/<sub>]]
        """
        if not parts or parts[0] not in ("api", "apis"):
            raise NotFound(f"unknown path {self.path}")
        # requested group/version drives response conversion (multi-version
        # serving, ref: runtime.Scheme conversion + negotiated serializers)
        if parts[0] == "api":
            self._req_version = parts[1] if len(parts) > 1 else "v1"
        else:
            self._req_version = "/".join(parts[1:3]) if len(parts) > 2 else ""
        rest = parts[2:] if parts[0] == "api" else parts[3:]
        if not rest:
            raise NotFound("missing resource")
        # /namespaces/<ns>/<resource>... is the namespaced form only when
        # <resource> is actually a registered resource — otherwise it's the
        # cluster-scoped namespaces object's own subresource
        # (/namespaces/<name>/status).
        if (
            rest[0] == "namespaces"
            and len(rest) >= 3
            and rest[2] in self.master.scheme.by_resource
        ):
            ns, resource = rest[1], rest[2]
            name = rest[3] if len(rest) > 3 else ""
            sub = rest[4] if len(rest) > 4 else ""
            return resource, ns, name, sub
        resource = rest[0]
        name = rest[1] if len(rest) > 1 else ""
        sub = rest[2] if len(rest) > 2 else ""
        return resource, "", name, sub

    def _handle(self, method: str):
        # fresh request on a (possibly reused keep-alive) connection: its
        # body is unread until _read_body / the proxy path consumes it
        self._body_consumed = False
        # request tracing (utils/spans): a client-sent X-Ktpu-Trace context
        # opens a server span around the whole request so the apiserver leg
        # of a pod's journey lands in /debug/traces under the pod's trace
        # id.  Watches are excluded (a span per hours-long stream is noise)
        # and so are plain GETs: reads dominate traffic at density
        # (informer lists, pre-heartbeat node gets) and would evict the
        # mutation spans forensics actually wants from the bounded
        # collector — the journey's legs are all writes (create, binding,
        # status, SLI patch).
        ctx = spanlib.parse_header(self.headers.get(spanlib.HEADER, ""))
        if ctx is None or method == "GET":
            return self._handle_inner(method)
        with self.master.spans.start_span(
                f"apiserver.{method}", parent=ctx, path=self.path):
            return self._handle_inner(method)

    def _handle_inner(self, method: str):
        # overload shedding FIRST: a mutating request past the inflight
        # bound is refused before it costs authn, admission, or a commit-
        # queue slot.  Reads (incl. watches) always pass — they're served
        # off the cacher.
        limiter = self.master.inflight
        if not limiter.acquire(method):
            err = TooManyRequests(
                "apiserver overloaded: too many in-flight mutating "
                "requests; retry after the indicated backoff")
            err.retry_after = limiter.retry_after()
            flightrec.note("apiserver", flightrec.SHED_429,
                           method=method, path=self.path,
                           retry_after=round(err.retry_after, 3))
            try:
                # _send_error drains the unread request body before
                # answering — shedding happens before any read, and the
                # leftover bytes would poison the keep-alive stream
                self._send_error(err)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # shed client already gone
            return
        try:
            self._handle_limited(method)
        finally:
            limiter.release(method)

    def _handle_limited(self, method: str):
        start = time.monotonic()
        try:
            parts, q = self._route()
            if parts and parts[0] in ("healthz", "readyz", "livez"):
                self._send_json(200, {"status": "ok"})
                return
            if parts and parts[0] == "version":
                self._send_json(200, {"gitVersion": "v0.1.0-ktpu", "platform": "tpu"})
                return
            user = self._authn()
            # legacy single-token mode: the shared secret IS the cluster
            # (a verified client certificate is an equally strong credential)
            if self.master.token and self.master.authorization_mode == "AlwaysAllow":
                if self._peer_cert_user() is None:
                    if self.headers.get("Authorization", "") != f"Bearer {self.master.token}":
                        raise Unauthorized("invalid bearer token")
                    user = UserInfo(name="system:admin", groups=[GROUP_MASTERS])
            self._user = user
            # aggregation: /apis/<group>/<version> claimed by an APIService
            # with a backing service proxies to that server (kube-aggregator).
            # Authorize against the parsed resource path BEFORE proxying —
            # upstream's aggregator likewise authorizes, then forwards
            # identity via front-proxy headers.
            apisvc = (
                self.master.find_apiservice(parts[1], parts[2])
                if len(parts) >= 3 and parts[0] == "apis"
                else None
            )
            if apisvc is not None:
                a_resource, a_ns, a_name, a_sub = self._parse_resource_path(parts)
                self._authz(
                    user,
                    verb_for(method, a_name, q.get("watch") in ("1", "true")),
                    a_resource, a_ns, a_name, a_sub,
                )
                self._proxy_to_apiservice(apisvc, method)
                return
            # SelfSubjectAccessReview (ref: pkg/registry/authorization/
            # selfsubjectaccessreview): any authenticated user may ask what
            # THEY can do — the answer evaluates the server's own
            # authorizer chain, which is what `kubectl auth can-i` wraps
            if (method == "POST" and len(parts) == 4 and parts[0] == "apis"
                    and parts[1] == "authorization.k8s.io"
                    and parts[3] == "selfsubjectaccessreviews"):
                attrs = ((self._read_body().get("spec") or {})  # ktpulint: ignore[KTPU009] SelfSubjectAccessReview wire shape — no registered dataclass
                         .get("resourceAttributes") or {})
                allowed = self.master.authorizer.authorize(
                    user,
                    attrs.get("verb", "get"), attrs.get("resource", ""),
                    attrs.get("namespace", ""), attrs.get("name", ""),
                    sub=attrs.get("subresource", ""))
                self._send_json(201, {
                    "kind": "SelfSubjectAccessReview",
                    "apiVersion": "authorization.k8s.io/v1",
                    "status": {"allowed": bool(allowed)},
                })
                return
            if parts and parts[0] == "metrics":
                self._serve_metrics()
                return
            if parts and parts[0] == "debug":
                from ..utils.debug import handle_debug

                # pprof is sensitive (stack contents) and expensive (the
                # profiler burns a thread per request): authorize like a
                # cluster-scoped resource read — anonymous RBAC users are
                # denied exactly as they are for every real resource
                self._authz(user, "get", "debug", "", "", "")
                if parts == ["debug", "traces"]:
                    body = self.master.spans.to_json(q.get("trace", ""))
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts == ["debug", "flightrecorder"]:
                    body = flightrec.to_json(q.get("component", ""))
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                res = handle_debug("/" + "/".join(parts), q)
                if res is None:
                    raise NotFound(f"unknown path {self.path}")
                status, ctype, body = res
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts == ["api", "v1", "bindstream"] and method == "GET":
                # persistent zero-copy bind leg (client/bindstream.py):
                # the upgrade rides a GET so it is never shed at accept
                # (reads aren't), but every ROUND inside the stream
                # acquires a mutating inflight slot and authorizes as
                # create pods/binding — stream framing must not become a
                # side door around overload control or the subresource
                # permission model
                self._serve_bindstream(q)
                return
            resource, ns, name, sub = self._parse_resource_path(parts)
            if resource not in self.master.scheme.by_resource:
                raise NotFound(f"resource {resource!r} not registered")
            verb = verb_for(method, name, q.get("watch") in ("1", "true"))
            if (method == "POST" and resource == "pods"
                    and name == "bindings:batch" and not sub):
                # a bulk bind is N binding-subresource creates: it must be
                # gated by the SAME pods/binding permission as a singleton
                # bind — authorizing it as plain `create pods` would let a
                # pod-creating principal bind arbitrary pods (the exact
                # escalation the subresource naming exists to prevent),
                # and a scheduler granted only pods/binding would 403
                self._authz(user, "create", resource, ns, "", "binding")
            elif (method == "POST" and resource == "pods"
                    and name == "delete:batch" and not sub):
                # a batch delete is N DELETEs: gate it with the same
                # `delete pods` permission as the singleton verb — the
                # POST transport must not let a create-only principal
                # delete pods (the bindings:batch rule, delete flavor)
                self._authz(user, "delete", resource, ns, "", "")
            else:
                self._authz(user, verb, resource, ns, name, sub)
            handler = getattr(self, f"_do_{method.lower()}")
            handler(resource, ns, name, sub, q)
            if method != "GET":
                # a just-written admission input must be enforced on the
                # very next request; the generation bump also voids any
                # in-flight stale scan racing this write
                if resource in ("mutatingwebhookconfigurations",
                                "validatingwebhookconfigurations"):
                    self.master._webhook_cache.invalidate()
                elif resource == "podpresets":
                    self.master._podpreset_cache.invalidate()
                elif resource == "podsecuritypolicies":
                    self.master._psp_cache.invalidate()
            self.master.metrics.observe(method, resource, time.monotonic() - start)
        except ApiError as e:
            try:
                self._send_error(e)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            traceback.print_exc()
            try:
                err = ApiError(str(e))
                self._send_error(err)
            except OSError:
                pass  # client connection already gone

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_PUT(self):
        self._handle("PUT")

    def do_PATCH(self):
        self._handle("PATCH")

    def do_DELETE(self):
        self._handle("DELETE")

    def _enc(self, obj):
        """Encode a response object in the REQUESTED API version when a
        conversion is registered (multi-version serving); the internal/hub
        form otherwise."""
        return self.master.scheme.encode(
            obj, version=getattr(self, "_req_version", ""))

    def _with_quota_serialization(self, resource: str, ns: str, write_fn):
        """Quota-counted writes serialize admission-check + commit so two
        concurrent writes cannot both pass a nearly-exhausted quota
        (admission computes usage from the store; unserialized it's TOCTOU).
        One helper for POST/PUT/PATCH so the rule can't drift per-verb."""
        effective_ns = ns or "default"
        if resource in ResourceQuotaAdmission.COUNTED and self.master._list_quotas(
            effective_ns
        ):
            with self.master.quota_lock:
                return write_fn()
        return write_fn()

    # ------------------------------------------------------------------ GET

    def _do_get(self, resource, ns, name, sub, q):
        if (resource == "pods" and sub
                and getattr(self, "_req_version", "")
                == t.PodCustomMetrics.API_VERSION):
            # aggregated custom-metrics read path (the custom.metrics.
            # k8s.io GET shape): /apis/custom.metrics.k8s.io/v1/
            # namespaces/<ns>/pods/<name-or-*>/<metric> answers a
            # MetricValueList off the PodCustomMetrics collection the
            # kubelets publish.  Authorized upstream as `get pods`
            # subresource <metric> — the generic path already ran it.
            self._serve_custom_metrics(ns, name, sub, q)
            return
        if name and not sub:
            self._get_object(resource, ns, name)
            return
        if resource == "pods" and sub == "log":
            self._proxy_pod_log(ns, name, q)
            return
        if resource == "pods" and sub.lower() in ("exec", "attach", "portforward"):
            self._proxy_pod_stream(ns, name, sub)
            return
        if name and sub:
            raise NotFound(f"subresource {sub!r} not readable")
        if q.get("watch") in ("1", "true"):
            self._serve_watch(resource, ns, q)
            return
        self._list_objects(resource, ns, q)

    def _get_object(self, resource, ns, name):
        """Single-object GET from the watch cache: committed wire dict ->
        cached bytes, zero decode/encode.  Falls back to the store when
        the cache can't answer fresh (still seeding, pump behind) — and
        before answering 404 on a cache miss in remote-store mode, where
        stream-progress freshness means a PEER apiserver's create may not
        have reached this cache yet (the upstream get-from-etcd-on-miss
        shape; existing objects — the hot path — never pay it)."""
        reg = self.master.registry
        try:
            raw = self.master.cacher.get_raw(reg.key(resource, ns, name))
        except CacheNotReady:
            self._send_obj(200, reg.get(resource, ns, name))
            return
        if raw is None:
            if self.master.store_is_remote:
                self._send_obj(200, reg.get(resource, ns, name))  # authoritative
                return
            raise NotFound(f'{resource} "{name}" not found')
        self._send_raw_json(200, self.master.scheme.encode_bytes(
            raw, getattr(self, "_req_version", "")))

    def _list_objects(self, resource, ns, q):
        """LIST from the watch cache: selector predicates run on the raw
        wire dicts and the response body is assembled from per-object
        cached bytes — one serialization per (object, revision) across
        every list, get, and watch frame that touches it.

        Pagination (`limit=`/`continue=`): chunks cursor over the sorted
        storage keys; the opaque token carries the FIRST chunk's
        resourceVersion (the client's watch-resume anchor — resuming the
        watch there replays every event the later chunks raced, and the
        client upserts the re-deliveries idempotently) plus the last key
        served.  A token whose anchor revision fell below the watch
        cache's history floor can no longer promise a gap-free relist:
        410 Expired, clean client restart.  No limit and no token keeps
        the exact single-body path — byte-identical wire at shards=1."""
        master = self.master
        scheme = master.scheme
        reg = master.registry
        label_selector = q.get("labelSelector", "")
        field_selector = q.get("fieldSelector", "")
        kind = scheme.by_resource[resource].KIND + "List"
        ver = getattr(self, "_req_version", "")
        try:
            limit = int(q.get("limit") or 0)
        except ValueError:
            raise BadRequest(f"invalid limit {q.get('limit')!r}") from None
        if limit < 0:
            raise BadRequest(f"limit must be >= 0, got {limit}")
        token = q.get("continue", "")
        anchor_rv, start_key = ("", "")
        if token:
            anchor_rv, start_key = decode_continue(token)
            self._check_continue_fresh(anchor_rv)
        try:
            entries, rev, match = reg.select_entries(
                master.cacher, resource, ns,
                label_selector=label_selector,
                field_selector=field_selector)
        except CacheNotReady:
            # authoritative fallback: raw store entries through the same
            # selector+pagination path (the store has no selector indexes
            # — unindexed scan — but the wire contract stays whole)
            entries, rev, match = reg.select_entries(
                master.store, resource, ns,
                label_selector=label_selector,
                field_selector=field_selector)
        next_token = ""
        if start_key:
            # entries are key-sorted (store and cache both list sorted):
            # bisect to strictly after the last served key — a continue
            # chunk must not re-walk the already-served head
            entries = entries[bisect.bisect_right(
                [e[0] for e in entries], start_key):]
            master.registry.note_list_continue()
        if limit:
            # lazy filtering: stop at limit+1 survivors — a chunk costs
            # O(entries scanned to fill it), never a full-collection
            # selector pass per continue round
            page, more = [], False
            for e in entries:
                if match is not None and not match(e[2]):
                    continue
                if len(page) == limit:
                    more = True
                    break
                page.append(e)
            entries = page
            if more:
                # the anchor rv is minted by the FIRST chunk and carried
                # through unchanged — it is the rv the informer will
                # resume its watch from, so it must predate everything
                # pagination might miss
                next_token = encode_continue(anchor_rv or str(rev),
                                             entries[-1][0])
        elif match is not None:
            entries = [e for e in entries if match(e[2])]
        dicts = [d for _k, _r, d in entries]
        # the List envelope carries the version the items are encoded in —
        # envelope/items disagreement breaks version-trusting decoders
        list_version = (scheme.converted_api_version(dicts[0], ver)
                        if dicts else ver or "v1")
        meta = '"resourceVersion":"%s"' % rev
        if next_token:
            meta += ',"continue":"%s"' % next_token
        head = ('{"kind":"%s","apiVersion":"%s",'
                '"metadata":{%s},"items":['
                % (kind, list_version, meta)).encode()
        body = head + b",".join(
            scheme.encode_bytes(d, ver) for d in dicts) + b"]}"
        self._send_raw_json(200, body)

    def _check_continue_fresh(self, anchor_rv: str):
        """410 a continue token whose watch-resume anchor can no longer
        be served gap-free.  Parts below the shard count are empty-shard
        floor sentinels (the plan_resume rule) — nothing to check."""
        try:
            parsed = parse_rv(anchor_rv)
        except ValueError:
            raise BadRequest(
                f"invalid continue token revision {anchor_rv!r}") from None
        floors = self.master.cacher.compacted_revisions()
        parts = parsed if isinstance(parsed, tuple) else (parsed,)
        if len(parts) != len(floors):
            raise TooOldResourceVersion(
                f"continue token arity {len(parts)} does not match shard "
                f"count {len(floors)}; restart the list")
        n = len(floors)
        for p, floor in zip(parts, floors):
            if p >= n and p < floor:
                raise TooOldResourceVersion(
                    f"continue token revision {p} compacted "
                    f"(floor {floor}); restart the list")

    # ------------------------------------------ custom-metrics read path

    def _serve_custom_metrics(self, ns, name, metric, q):
        """GET /apis/custom.metrics.k8s.io/v1/namespaces/<ns>/pods/
        <name-or-*>/<metric> — the aggregated custom-metrics API shape:
        one MetricValueList row per pod whose PodCustomMetrics carries
        the named sample.  ``labelSelector`` selects over the metrics
        objects' labels (the kubelet copies the pod's labels onto them,
        so selecting the metrics collection IS selecting the pods).
        Stale rows (the owning kubelet's last scrape failed) are
        FORWARDED with ``stale: true``, never silently dropped —
        holding-vs-discarding a stale signal is the consumer's policy
        decision (the HPA holds)."""
        master = self.master
        reg = master.registry
        scheme = master.scheme
        if not ns:
            raise BadRequest("custom metrics are namespaced: "
                             "/namespaces/<ns>/pods/<name>/<metric>")
        label_selector = q.get("labelSelector", "")
        try:
            entries, rev, match = reg.select_entries(
                master.cacher, "podcustommetrics", ns,
                label_selector=label_selector)
        except CacheNotReady:
            entries, rev, match = reg.select_entries(
                master.store, "podcustommetrics", ns,
                label_selector=label_selector)
        items = []
        for _k, _r, d in entries:
            if match is not None and not match(d):
                continue
            if name and name != "*" \
                    and d.get("metadata", {}).get("name") != name:
                continue  # filter on the raw dict — don't decode 5000
                # namespace objects to answer a single-pod query
            pcm = scheme.decode(d)
            value = appmetrics.sample_value(pcm, metric)
            if value is None:
                continue
            items.append({
                "describedObject": {
                    "kind": "Pod",
                    "namespace": pcm.metadata.namespace,
                    "name": pcm.metadata.name,
                },
                "metricName": metric,
                "value": value,
                "timestamp": pcm.timestamp,
                "stale": pcm.stale,
            })
        if name and name != "*" and not items:
            raise NotFound(
                f'no sample {metric!r} for pod "{ns}/{name}" '
                f"(not scraped, or the metric is not exported)")
        self._send_json(200, {
            "kind": "MetricValueList",
            "apiVersion": t.PodCustomMetrics.API_VERSION,
            "metadata": {"resourceVersion": str(rev)},
            "items": items,
        })

    # --------------------------------------- kubelet proxy (exec/logs/etc.)

    def _kubelet_endpoint(self, node_name: str):
        """(host, port, bearer token) for a node's kubelet server.  The
        token comes from the node's kube-system Secret — the apiserver is
        the trusted hop (ref: apiserver→kubelet connection for
        exec/logs/proxy, SURVEY §1)."""
        node = self.master.registry.get("nodes", "", node_name)
        url = (node.metadata.annotations or {}).get("kubelet.ktpu.io/server")
        if not url:
            raise NotFound(f"node {node_name} advertises no kubelet endpoint")
        try:
            sec = self.master.registry.get(
                "secrets", "kube-system", f"kubelet-token-{node_name}")
            token = sec.data.get("token", "")
        except NotFound:
            token = ""
        parsed = urlparse(url)
        return parsed.hostname, parsed.port, token, parsed.scheme == "https"

    def _kubelet_ssl_context(self):
        """Verify the kubelet's serving cert against the cluster CA (the
        CSR signer issued it); unverified TLS only when this apiserver has
        no CA configured.  One shared context — the CA is immutable for the
        Master's lifetime."""
        ctx = self.master._kubelet_client_ctx
        if ctx is None:
            import ssl as _ssl

            ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            if self.master.client_ca_file:
                ctx.load_verify_locations(cafile=self.master.client_ca_file)
            else:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
            self.master._kubelet_client_ctx = ctx
        return ctx

    def _scheduled_pod(self, ns: str, name: str):
        pod = self.master.registry.get("pods", ns, name)
        if not pod.spec.node_name:
            raise BadRequest(f"pod {ns}/{name} is not scheduled to a node")
        return pod

    def _proxy_pod_log(self, ns: str, name: str, q):
        """GET pods/<name>/log — the reference's apiserver→kubelet log
        fetch (registry/core/pod/rest/log.go)."""
        import http.client as _http

        pod = self._scheduled_pod(ns, name)
        host, port, token, tls = self._kubelet_endpoint(pod.spec.node_name)
        container = q.get("container") or pod.spec.containers[0].name
        path = f"/containerLogs/{ns}/{name}/{container}"
        if q.get("tailLines"):
            path += f"?tail={int(q['tailLines'])}"
        if tls:
            conn = _http.HTTPSConnection(host, port, timeout=30,
                                         context=self._kubelet_ssl_context())
        else:
            conn = _http.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("GET", path,
                         headers={"Authorization": f"Bearer {token}"})
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        self.send_response(resp.status)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _proxy_pod_stream(self, ns: str, name: str, sub: str):
        """exec/attach/portForward: authorize per-verb at the apiserver,
        then splice the upgraded client connection onto the kubelet's —
        the credential for the kubelet hop never reaches the client."""
        from ..utils import streams

        kind = {"exec": "exec", "attach": "attach",
                "portforward": "portForward"}[sub.lower()]
        pod = self._scheduled_pod(ns, name)
        host, port, token, tls = self._kubelet_endpoint(pod.spec.node_name)
        parsed = urlparse(self.path)
        rq = parse_qs(parsed.query)
        if kind == "portForward":
            kpath = f"/portForward/{ns}/{name}"
        else:
            container = (rq.get("container") or [""])[0] \
                or pod.spec.containers[0].name
            kpath = f"/{kind}/{ns}/{name}/{container}"
        if parsed.query:
            kpath += f"?{parsed.query}"
        try:
            upstream = streams.upgrade_request(
                host, port, kpath, {"Authorization": f"Bearer {token}"},
                ssl_context=self._kubelet_ssl_context() if tls else None)
        except (OSError, ConnectionError) as e:
            raise BadRequest(f"kubelet connection failed: {e}") from None
        client_sock = streams.accept_upgrade(self)
        if client_sock is None:
            upstream.close()
            raise BadRequest("expected Connection: Upgrade, "
                             "Upgrade: ktpu-stream")
        try:
            streams.splice(client_sock, upstream)
        finally:
            upstream.close()

    def _serve_bindstream(self, q):
        """Persistent bulk-bind stream (the scheduler's zero-copy bind
        leg): after the ktpu-bind Upgrade handshake, the connection
        speaks length-prefixed codec frames both ways (storage/wire.
        BinFramer — the store wire's framing).  One request frame = one
        bindings:batch round through the SAME registry path as the HTTP
        endpoint; per-round outcomes ship back as one response frame.

        Failure semantics: a frame dispatches only when complete, so a
        client dying mid-send can never half-bind; a torn/overlong frame
        or clean close ends the stream (the client falls back to the
        per-request HTTP path).  Per-round errors — authz, shed (429 +
        retryAfterSeconds), malformed envelope — answer an {"error"}
        frame on a healthy stream."""
        from ..machinery.codec import CodecError, known_codecs
        from ..storage.wire import BinFramer
        from ..utils import streams as _streams

        codec_id = q.get("codec", "json")
        if codec_id not in known_codecs():
            raise BadRequest(f"unsupported bind stream codec {codec_id!r}")
        sock = _streams.accept_upgrade(self, proto="ktpu-bind")
        if sock is None:
            raise BadRequest(
                "expected Connection: Upgrade, Upgrade: ktpu-bind")
        master = self.master
        f = sock.makefile("rwb")
        framer = BinFramer(f, codec_id, site="apiserver.bindstream")
        try:
            while not master.stopping.is_set():
                try:
                    req = framer.recv()
                except (ConnectionError, CodecError, OSError, ValueError):
                    break  # client gone, or a torn/corrupt frame
                try:
                    resp = self._bindstream_round(req)
                except ApiError as e:
                    resp = {"error": e.to_status()}
                except Exception as e:  # noqa: BLE001 — keep the stream up
                    traceback.print_exc()
                    resp = {"error": ApiError(str(e)).to_status()}
                try:
                    framer.send(resp)
                except (ConnectionError, OSError):
                    break
        finally:
            try:
                f.close()
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _bindstream_round(self, req) -> Dict[str, Any]:
        """One bulk-bind round: authorize (create pods/binding in the
        envelope's namespace — the bindings:batch rule), shed past the
        mutating inflight bound, commit through Registry.bind_batch."""
        master = self.master
        ns = str(req.get("namespace") or "")
        items = req.get("items")
        if not isinstance(items, list) or not items:
            raise BadRequest("bind stream round requires items")
        self._authz(self._user, "create", "pods", ns, "", "binding")
        limiter = master.inflight
        if not limiter.acquire("POST"):
            err = TooManyRequests(
                "apiserver overloaded: too many in-flight mutating "
                "requests; retry after the indicated backoff")
            retry_after = limiter.retry_after()
            flightrec.note("apiserver", flightrec.SHED_429,
                           method="BINDSTREAM", path="/api/v1/bindstream",
                           retry_after=round(retry_after, 3))
            status = err.to_status()
            status["retryAfterSeconds"] = round(retry_after, 3)
            return {"error": status}
        try:
            bindings = []
            for d in items:
                obj = master.scheme.decode(d)
                if getattr(obj, "KIND", "") != "Binding":
                    raise BadRequest(
                        f"bind stream items must be Binding, got "
                        f"{d.get('kind') if isinstance(d, dict) else d!r}")
                # the round was authorized against the ENVELOPE namespace;
                # an item naming another namespace would commit where the
                # authz check never looked (bind_batch falls back to the
                # item's own metadata.namespace)
                # (an EMPTY envelope namespace authorized cluster-wide,
                # where cross-namespace items are the legitimate shape)
                item_ns = obj.metadata.namespace
                if ns and item_ns and item_ns != ns:
                    raise Forbidden(
                        f"binding {obj.metadata.name!r} names namespace "
                        f"{item_ns!r}; the round authorized {ns!r}")
                bindings.append(obj)
            outcomes = master.registry.bind_batch(ns, bindings)
        finally:
            limiter.release("POST")
        master.audit("bind", "pods", ns, f"bindstream[{len(bindings)}]",
                     self._user.name)
        return {"results": [
            {"kind": "Status", "apiVersion": "v1", "status": "Success"}
            if e is None else e.to_status() for e in outcomes
        ]}

    def _serve_watch(self, resource, ns, q):
        try:
            # composite "r0.r1..." resourceVersions (sharded store:
            # per-shard resume positions) parse to a tuple; plain ints
            # stay ints — storage/shardmap.parse_rv
            since = parse_rv(q.get("resourceVersion"))
        except ValueError as e:
            raise BadRequest(f"invalid resourceVersion: {e}") from None
        if isinstance(since, tuple) and self.master.store_shards == 1:
            raise BadRequest(
                "composite resourceVersion presented to an unsharded "
                "apiserver; relist")
        timeout = float(q.get("timeoutSeconds") or 0)
        try:
            w = self.master.registry.watch(
                resource,
                ns,
                since_rev=since,
                label_selector=q.get("labelSelector", ""),
                field_selector=q.get("fieldSelector", ""),
                via=self.master.cacher,
            )
        except (CacheNotReady, TooOldResourceVersion):
            # Cache can't serve: still seeding / pump behind, OR the
            # resume revision predates the cache's window (an apiserver
            # restart seeds the window at the CURRENT revision while the
            # store's history ring may reach much further back).  Watch
            # the store directly — at the same configured queue bound —
            # instead of 410ing every reconnecting informer into a
            # synchronized relist storm; the store raises its own 410 if
            # the revision is truly compacted.
            w = self.master.registry.watch(
                resource,
                ns,
                since_rev=since,
                label_selector=q.get("labelSelector", ""),
                field_selector=q.get("fieldSelector", ""),
                queue_limit=self.master.watch_queue_limit,
            )
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        # the buffered response stream (wbufsize=-1) only auto-flushes when
        # a request COMPLETES — a watch never does, and the client blocks
        # in getresponse() until the headers actually hit the wire
        self.wfile.flush()
        deadline = time.monotonic() + timeout if timeout else None
        stream = _WatchStream(self.master, w, q,
                              ver=getattr(self, "_req_version", ""))
        if self.master.event_loop_serving:
            # event-loop serving: the headers are on the wire; hand the
            # socket off to the shared dispatcher and return this handler
            # thread to the pool.  From here the _WatchConn state machine
            # owns the stream.
            self._handoff_watch(stream, deadline)
            return
        self._serve_watch_threaded(stream, deadline)

    def _handoff_watch(self, stream: _WatchStream,
                       deadline: Optional[float]):
        """Detach the request socket from the HTTP server and adopt it
        onto the dispatcher.  The handler thread returns immediately;
        socketserver's shutdown_request is told to leave the socket
        alone (``_ApiHTTPServer.detach_request``) and the handler's
        ``finish()`` closing its makefile wrappers only drops dup'd
        references — the underlying fd survives."""
        # everything buffered so far (the chunked headers) must be on the
        # wire before the dispatcher takes over the fd
        self.wfile.flush()
        schedsan.preempt("apiserver.watch.handoff")
        self.server.detach_request(self.connection)
        self.close_connection = True
        conn = _WatchConn(self.master, stream, self.connection, deadline)
        self.master.adopt_watch_conn(conn)

    def _serve_watch_threaded(self, stream: _WatchStream,
                              deadline: Optional[float]):
        """The pre-event-loop serving leg: this handler thread parks in
        the blocking batch loop until the stream ends.  Kept as the A/B
        baseline (KTPU_EVENTLOOP=0) and as the golden-parity reference —
        the wire bytes here define what the dispatcher must emit."""
        w = stream.w
        try:
            while True:
                if deadline and time.monotonic() >= deadline:
                    break
                evs = w.next_batch_timeout(WATCH_HEARTBEAT_SECONDS)
                if self.master.stopping.is_set():
                    break
                if evs is None:
                    if getattr(w, "evicted", False):
                        # slow consumer (or cache reseed): this stream can
                        # no longer be gap-free.  Answer 410 Expired so
                        # the reflector relists — the reference cacher's
                        # eviction contract (storage/cacher.go).
                        self._write_chunk(stream.eviction_frame())
                        break
                    if getattr(w, "closed", False) or w._stopped.is_set():
                        # upstream (external store) stream died or the
                        # watcher was stopped server-side: END this
                        # client's watch so its reflector relists/rewatches
                        # — heartbeating a dead pipe would stall the
                        # cluster's control loops silently
                        break
                    # heartbeat chunk keeps half-open connections
                    # detectable; merged streams heartbeat with a
                    # bookmark so even an idle informer always holds a
                    # fresh composite resume position — and plain
                    # streams that opted in get the progress analog
                    # (None = no safe rv this tick; plain heartbeat)
                    self._write_chunk(stream.heartbeat_frame())
                    continue
                # A batch's frames go out as ONE buffered write + flush:
                # the syscall and the client's recv wakeup amortize
                # across the batch (frame construction: _WatchStream).
                self._write_chunks(stream.batch_frames(evs))
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            pass
        finally:
            w.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass  # watcher hung up mid-stream
            self.close_connection = True

    def _write_chunk(self, data: bytes):
        self._write_chunks([data])

    def _write_chunks(self, frames):
        """Frame N chunks and ship them as ONE buffered write + flush (a
        batch's worth of watch frames costs one syscall and one client
        recv wakeup; encoding: module-level ``_encode_chunks``)."""
        buf = _encode_chunks(frames)
        if buf:
            self.wfile.write(buf)
            self.wfile.flush()

    def _serve_metrics(self):
        master = self.master
        hits, misses = master.scheme.serialization_cache.stats()
        total = hits + misses
        evictions = (master.cacher.watch_evictions
                     + getattr(master.store, "watch_evictions", 0))
        extra = [
            "# TYPE ktpu_encode_cache_hits_total counter",
            f"ktpu_encode_cache_hits_total {hits}",
            "# TYPE ktpu_encode_cache_misses_total counter",
            f"ktpu_encode_cache_misses_total {misses}",
            "# TYPE ktpu_encode_cache_hit_ratio gauge",
            f"ktpu_encode_cache_hit_ratio "
            f"{(hits / total) if total else 0.0:.6f}",
            "# TYPE ktpu_watch_slow_consumer_evictions_total counter",
            f"ktpu_watch_slow_consumer_evictions_total {evictions}",
            "# TYPE ktpu_watch_cache_reseeds_total counter",
            f"ktpu_watch_cache_reseeds_total {master.cacher.reseeds}",
            "# TYPE ktpu_write_coalesce_waits_total counter",
            f"ktpu_write_coalesce_waits_total {master.write_coalescer.waits}",
            # robustness surface (BENCH_r06+ records these next to perf):
            # overload shedding + per-verb-class inflight gauges
            "# TYPE ktpu_apiserver_inflight gauge",
            f'ktpu_apiserver_inflight{{verb="mutating"}} '
            f'{master.inflight.inflight("mutating")}',
            f'ktpu_apiserver_inflight{{verb="readonly"}} '
            f'{master.inflight.inflight("readonly")}',
            "# TYPE ktpu_apiserver_shed_total counter",
            f"ktpu_apiserver_shed_total {master.inflight.shed_total}",
            # scheduler-sharding surface: binds refused because another
            # shard's pod holds the chip (the optimistic-concurrency
            # loser count; the winner's bind is invisible here)
            "# TYPE ktpu_bind_device_conflicts_total counter",
            f"ktpu_bind_device_conflicts_total "
            f"{master.registry.device_claim_conflicts}",
            # selector-LIST index + pagination economics (the 5000-node
            # read-path envelope): hits served in O(matches) off the
            # watch-cache secondary index, misses scanned the collection
            "# TYPE ktpu_list_index_hits_total counter",
            f"ktpu_list_index_hits_total {master.registry.list_index_hits}",
            "# TYPE ktpu_list_index_misses_total counter",
            f"ktpu_list_index_misses_total "
            f"{master.registry.list_index_misses}",
            "# TYPE ktpu_list_index_hit_ratio gauge",
            f"ktpu_list_index_hit_ratio "
            f"{_ratio(master.registry.list_index_hits, master.registry.list_index_misses):.6f}",
            "# TYPE ktpu_list_continue_total counter",
            f"ktpu_list_continue_total "
            f"{master.registry.list_continue_rounds}",
            # watch-dispatch economics (the fan-out half of the 5000-node
            # envelope): indexed_hits = deliveries routed through a
            # selector bucket; scans = (event x watcher) pairs walked on
            # the legacy scan leg.  hits + scans IS the per-commit
            # dispatch work — at 5000 single-node watchers it should sit
            # ~3 orders of magnitude under watchers x events.
            "# TYPE ktpu_watch_dispatch_indexed_hits_total counter",
            f"ktpu_watch_dispatch_indexed_hits_total "
            f"{getattr(master.cacher, 'dispatch_indexed_hits', 0)}",
            "# TYPE ktpu_watch_dispatch_scans_total counter",
            f"ktpu_watch_dispatch_scans_total "
            f"{getattr(master.cacher, 'dispatch_scans', 0)}",
            # bookmark frames emitted (composite + lag-stamp + progress):
            # the idle-watcher freshness surface — zero here while idle
            # informers later 410-relist means the opt-in never reached
            # the wire
            "# TYPE ktpu_watch_bookmarks_total counter",
            f"ktpu_watch_bookmarks_total {master.watch_bookmarks}",
            # event-loop serving surface: the thread-count win and the
            # dispatcher's health.  threads is the WHOLE process (handler
            # pool + pumps + worker pool) — at 10k hollow watchers it
            # stays bounded instead of ~10k; connections counts every
            # long-lived stream multiplexed on the shared dispatcher.
            "# TYPE ktpu_apiserver_threads gauge",
            f"ktpu_apiserver_threads {threading.active_count()}",
            "# TYPE ktpu_eventloop_connections gauge",
            f"ktpu_eventloop_connections {_eventloop.connection_count()}",
            # timer fire lag: a saturated dispatcher shows up HERE (late
            # heartbeats, stale scrapes) before clients notice
            _eventloop.loop_lag_seconds.render().rstrip("\n"),
        ]
        # cacher freshness-wait lag (obs plane): how long LIST/GET reads
        # blocked for watch-cache freshness.  Sharded cachers render a
        # per-shard p99 gauge (one hot shard must not hide in a merge);
        # the single cacher renders its full histogram.
        shard_cachers = getattr(master.cacher, "shard_cachers", None)
        if shard_cachers is not None:
            extra.append(
                "# TYPE ktpu_cacher_freshness_wait_p99_seconds gauge")
            for i, c in enumerate(shard_cachers):
                p99 = c.freshness_wait_seconds.quantile(0.99)
                extra.append(
                    f'ktpu_cacher_freshness_wait_p99_seconds'
                    f'{{shard="{i}"}} {p99 or 0.0}')
        else:
            extra.append(master.cacher.freshness_wait_seconds
                         .render().rstrip("\n"))
        if master.render_client_metrics:
            from ..client import informer as _informer
            from ..client import retry as _client_retry

            # every in-process client loop (informers, controllers,
            # kubelets in a LocalCluster) shares these module-level
            # metrics; remote components export them from their own
            # /metrics.  Exactly one Master per process renders them
            # (render_client_metrics) so a fleet merge over co-located
            # apiservers never double-counts.
            from ..client import bindstream as _bindstream

            extra.append(_client_retry.retries_total.render().rstrip("\n"))
            extra.append(
                _bindstream.bindstream_frames_total.render().rstrip("\n"))
            extra.append(
                _bindstream.bindstream_bytes_total.render().rstrip("\n"))
            extra.append(
                _bindstream.bindstream_fallbacks_total.render().rstrip("\n"))
            extra.append(
                _informer.informer_relists_total.render().rstrip("\n"))
            extra.append(
                _informer.informer_reconnects_total.render().rstrip("\n"))
            extra.append(
                _informer.informer_relist_bytes_total.render().rstrip("\n"))
            extra.append(
                _informer.informer_lag_seconds.render().rstrip("\n"))
            # gang failure-domain surface (module-level in
            # controllers/job.py, same aggregation contract as the retry
            # counter): member-death -> all-members-Running MTTR +
            # whole-gang recreate attempts
            from ..controllers import job as _job_ctrl

            extra.append(
                _job_ctrl.gang_recovery_seconds.render().rstrip("\n"))
            extra.append(
                _job_ctrl.gang_attempts_total.render().rstrip("\n"))
            # endpoints fan-out economics (module-level in
            # controllers/endpoints.py, same contract): writes vs pod
            # churn events absorbed by coalescing, and the oldest-event
            # -> Endpoints-write propagation-lag SLI
            from ..controllers import endpoints as _eps_ctrl

            extra.append(
                _eps_ctrl.endpoints_writes_total.render().rstrip("\n"))
            extra.append(
                _eps_ctrl.endpoints_coalesced_total.render().rstrip("\n"))
            extra.append(
                _eps_ctrl.endpoints_propagation_seconds
                .render().rstrip("\n"))
            # autoscaling loop surface (module-level in controllers/
            # podautoscaler.py, same contract): observed metric values,
            # desired vs current replicas, rescales, and the
            # out-of-band -> rescale-landed reaction-time SLI
            from ..controllers import podautoscaler as _hpa_ctrl

            extra.append(
                _hpa_ctrl.hpa_observed_value.render().rstrip("\n"))
            extra.append(
                _hpa_ctrl.hpa_desired_replicas.render().rstrip("\n"))
            extra.append(
                _hpa_ctrl.hpa_current_replicas.render().rstrip("\n"))
            extra.append(
                _hpa_ctrl.hpa_rescales_total.render().rstrip("\n"))
            extra.append(
                _hpa_ctrl.hpa_missing_metric_cycles_total
                .render().rstrip("\n"))
            extra.append(
                _hpa_ctrl.hpa_reaction_seconds.render().rstrip("\n"))
        # write-path economics (in-process store only; a remote store
        # exports these from its own process): group-commit occupancy and
        # the fan-out coalescing ratio — wakeups-per-event < 1.0 means
        # watcher/replica/cacher wakeups are being amortized across
        # batched commits (the BENCH_r06 acceptance metric)
        commits = (getattr(master.store, "commit_count", None)
                   if master.render_store_metrics else None)
        if commits is not None:
            batches = master.store.commit_batches
            # client watchers hang off the CACHER in-process (the store's
            # own watcher list is empty in sync-feed mode): aggregate both
            # fan-out layers so the ratio reflects what clients cost
            wakeups = (master.store.watch_wakeups
                       + master.cacher.watch_wakeups)
            events = (master.store.watch_events
                      + master.cacher.watch_events)
            extra += [
                "# TYPE ktpu_store_commits_total counter",
                f"ktpu_store_commits_total {commits}",
                "# TYPE ktpu_store_commit_batches_total counter",
                f"ktpu_store_commit_batches_total {batches}",
                "# TYPE ktpu_store_batch_occupancy gauge",
                f"ktpu_store_batch_occupancy "
                f"{(commits / batches) if batches else 0.0:.6f}",
                # deletion-path economics (the churn envelope): delete ops
                # per delete-carrying caller batch — ~1.0 means the hot
                # delete callers are still issuing singletons
                "# TYPE ktpu_store_delete_batch_ops_total counter",
                f"ktpu_store_delete_batch_ops_total "
                f"{getattr(master.store, 'delete_batch_ops', 0)}",
                "# TYPE ktpu_store_delete_batches_total counter",
                f"ktpu_store_delete_batches_total "
                f"{getattr(master.store, 'delete_batches', 0)}",
                "# TYPE ktpu_store_delete_batch_occupancy gauge",
                f"ktpu_store_delete_batch_occupancy "
                f"{(getattr(master.store, 'delete_batch_ops', 0) / getattr(master.store, 'delete_batches', 1)) if getattr(master.store, 'delete_batches', 0) else 0.0:.6f}",
                "# TYPE ktpu_store_watch_wakeups_total counter",
                f"ktpu_store_watch_wakeups_total {wakeups}",
                "# TYPE ktpu_store_watch_events_total counter",
                f"ktpu_store_watch_events_total {events}",
                "# TYPE ktpu_store_watch_wakeups_per_event gauge",
                f"ktpu_store_watch_wakeups_per_event "
                f"{(wakeups / events) if events else 0.0:.6f}",
                "# TYPE ktpu_wal_torn_tail_repairs_total counter",
                f"ktpu_wal_torn_tail_repairs_total "
                f"{getattr(master.store, 'wal_torn_tail_repairs', 0)}",
                master.store.wal_fsync_seconds.render().rstrip("\n"),
            ]
            if isinstance(master.store, ShardedStore):
                # per-shard write-path economics (in-process sharding):
                # the aggregate occupancy above can hide one hot shard —
                # these lines keep the partition honest on /metrics
                extra.append("# TYPE ktpu_store_shard_commits_total counter")
                for i, shard in enumerate(master.store.shard_stores):
                    extra.append(
                        f'ktpu_store_shard_commits_total{{shard="{i}"}} '
                        f'{getattr(shard, "commit_count", 0)}')
                extra.append(
                    "# TYPE ktpu_store_shard_commit_batches_total counter")
                for i, shard in enumerate(master.store.shard_stores):
                    extra.append(
                        f'ktpu_store_shard_commit_batches_total'
                        f'{{shard="{i}"}} '
                        f'{getattr(shard, "commit_batches", 0)}')
                extra.append("# TYPE ktpu_store_shard_wal_fsync_p99_seconds"
                             " gauge")
                for i, shard in enumerate(master.store.shard_stores):
                    hist = getattr(shard, "wal_fsync_seconds", None)
                    p99 = hist.quantile(0.99) if hist is not None else None
                    extra.append(
                        f'ktpu_store_shard_wal_fsync_p99_seconds'
                        f'{{shard="{i}"}} {p99 or 0.0}')
        body = (master.metrics.render() + "\n".join(extra) + "\n").encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ----------------------------------------------------------------- POST

    def _do_post(self, resource, ns, name, sub, q):
        reg = self.master.registry
        body = self._read_body()
        if resource == "pods" and name == "bindings:batch" and not sub:
            # bulk bind: every member binding of a gang (or a drained
            # scheduler bind queue) lands in ONE store group commit —
            # per-item outcomes, HTTP 200 for the envelope
            bindings = []
            for d in body.get("items") or []:
                obj = self.master.scheme.decode(d)
                if getattr(obj, "KIND", "") != "Binding":
                    raise BadRequest(
                        f"bindings:batch items must be Binding, got "
                        f"{d.get('kind')!r}")
                # authorized against the URL namespace only: an item
                # naming another namespace would commit where the authz
                # check never looked (the scheduler groups bulk binds by
                # namespace, so legitimate traffic never trips this)
                # (the no-namespace URL form authorized cluster-wide,
                # where cross-namespace items are the legitimate shape)
                item_ns = obj.metadata.namespace
                if ns and item_ns and item_ns != ns:
                    raise Forbidden(
                        f"binding {obj.metadata.name!r} names namespace "
                        f"{item_ns!r}; the request authorized {ns!r}")
                bindings.append(obj)
            if not bindings:
                raise BadRequest("bindings:batch requires items")
            outcomes = reg.bind_batch(ns, bindings)
            self.master.audit("bind", resource, ns,
                              f"bindings:batch[{len(bindings)}]",
                              self._user.name)
            self._send_json(200, {
                "kind": "BindingBatchResult", "apiVersion": "v1",
                "results": [
                    {"kind": "Status", "apiVersion": "v1",
                     "status": "Success"} if e is None else e.to_status()
                    for e in outcomes
                ],
            })
            return
        if resource == "pods" and name == "delete:batch" and not sub:
            # batched deletion: the deletion half of the group-commit
            # write path — N pod deletes/finalize-marks land through one
            # store group commit, per-item Status outcomes, HTTP 200 for
            # the envelope (amortization, not a transaction)
            items = []
            for d in body.get("items") or []:
                item_ns = d.get("namespace") or ""
                if ns and item_ns and item_ns != ns:
                    # same rule as bindings:batch: an item naming another
                    # namespace would delete where the authz never looked
                    raise Forbidden(
                        f"delete item {d.get('name')!r} names namespace "
                        f"{item_ns!r}; the request authorized {ns!r}")
                grace = d.get("gracePeriodSeconds")
                items.append({
                    "name": d.get("name") or "",
                    "namespace": item_ns or ns,
                    "grace_seconds": None if grace is None else int(grace),
                    "resource_version": d.get("resourceVersion") or "",
                })
            if not items:
                raise BadRequest("delete:batch requires items")
            outcomes = reg.delete_batch("pods", ns, items)
            flightrec.note(
                "apiserver", flightrec.DELETE_BATCH, ns=ns,
                items=len(items),
                errors=sum(1 for e in outcomes if e is not None))
            self.master.audit("delete", resource, ns,
                              f"delete:batch[{len(items)}]",
                              self._user.name)
            self._send_json(200, {
                "kind": "DeleteBatchResult", "apiVersion": "v1",
                "results": [
                    {"kind": "Status", "apiVersion": "v1",
                     "status": "Success"} if e is None else e.to_status()
                    for e in outcomes
                ],
            })
            return
        if resource == "pods" and sub == "binding":
            binding = self.master.scheme.decode(body)
            reg.bind(ns, name, binding)
            self.master.audit("bind", resource, ns, name, self._user.name)
            # upstream returns a Status for binding creates, not the pod
            # (registry/core/pod/storage BindingREST) — also keeps the
            # hottest write path's response O(1) instead of a pod encode
            self._send_json(201, {"kind": "Status", "apiVersion": "v1",
                                  "status": "Success"})
            return
        if resource == "pods" and sub == "eviction":
            eviction = None
            if body:
                if body.get("kind") not in (None, "", "Eviction"):
                    raise BadRequest(
                        f"eviction body must be kind Eviction, got {body.get('kind')!r}"
                    )
                decoded = self.master.scheme.decode(body)
                if hasattr(decoded, "grace_period_seconds"):
                    eviction = decoded
            evicted = reg.evict(ns, name, eviction)
            self.master.audit("evict", resource, ns, name, self._user.name)
            self._send_obj(201, evicted)
            return
        if sub:
            raise NotFound(f"subresource {sub!r} not writable")
        obj = self.master.scheme.decode(body)
        self._check_kind(resource, obj)
        # default namespace from the URL before admission so plugins
        # (NamespaceAutoProvision) see the effective namespace
        if ns and not obj.metadata.namespace:
            obj.metadata.namespace = ns
        if resource == "pods":
            # observability stamps (server-set): the creating request's
            # trace id rides the object through the watch path, and the
            # creation wall time anchors the pod-startup SLI decomposition
            # (utils/slo) — now_iso's 1s resolution is too coarse for it
            tid = spanlib.current_trace_id()
            if tid:
                obj.metadata.annotations.setdefault(
                    t.TRACE_ID_ANNOTATION, tid)
            obj.metadata.annotations.setdefault(
                t.CREATED_AT_ANNOTATION, f"{time.time():.6f}")  # ktpulint: ignore[KTPU005] cross-process SLI wall stamp

        def admit_and_create():
            nonlocal obj
            obj = self.master.admission.admit(CREATE, resource, obj, user=self._user)
            return reg.create(resource, ns, obj)

        # coalescer gate BEFORE the quota lock: parking happens with no
        # locks held, then the burst's handlers hit the store together
        # and its group commit drains them as one batch
        with self.master.write_coalescer:
            created = self._with_quota_serialization(
                resource, ns or obj.metadata.namespace, admit_and_create
            )
        # audit with the effective namespace: creates may carry the ns only
        # in the object body (no-ns URL form), and namespace-scoped audit
        # rules must still match those writes
        self.master.audit("create", resource,
                          ns or created.metadata.namespace,
                          created.metadata.name,
                          self._user.name, request_obj=body,
                          response_obj=lambda: self.master.scheme.encode(created))
        if resource == "customresourcedefinitions":
            self.master.apply_crd(created)
        elif resource == "apiservices":
            self.master.apply_apiservice(created)
        self._send_obj(201, created)

    # ------------------------------------------------------------------ PUT

    def _do_put(self, resource, ns, name, sub, q):
        reg = self.master.registry
        body = self._read_body()
        obj = self.master.scheme.decode(body)
        self._check_kind(resource, obj)
        if sub == "status":
            updated = reg.update_status(resource, ns, name, obj)
        elif sub:
            raise NotFound(f"subresource {sub!r} not writable")
        else:
            old = reg.get(resource, ns, name)

            def admit_and_update():
                nonlocal obj
                obj = self.master.admission.admit(
                    UPDATE, resource, obj, old, user=self._user
                )
                return reg.update(resource, ns, name, obj)

            with self.master.write_coalescer:
                updated = self._with_quota_serialization(
                    resource, ns or old.metadata.namespace, admit_and_update
                )
            if resource == "customresourcedefinitions":
                self.master.remove_crd(old)
                self.master.apply_crd(updated)
            elif resource == "apiservices":
                self.master.remove_apiservice(old)
                self.master.apply_apiservice(updated)
        self.master.audit("update", resource, ns, name, self._user.name,
                          request_obj=body,
                          response_obj=lambda: self.master.scheme.encode(updated))
        self._send_obj(200, updated)

    # ---------------------------------------------------------------- PATCH

    def _do_patch(self, resource, ns, name, sub, q):
        patch = self._read_body()
        if sub == "status":
            patch = {"status": patch.get("status", patch)}
        old = None
        if resource in ("customresourcedefinitions", "apiservices"):
            old = self.master.registry.get(resource, ns, name)
        # the admission chain runs on the merged object exactly as on PUT —
        # a patch must not bypass LimitRange/quota/NodeRestriction (the
        # reference admits updates and patches through the same chain)
        admit = lambda merged, cur: self.master.admission.admit(  # noqa: E731
            UPDATE, resource, merged, cur, user=self._user
        )
        updated = self._with_quota_serialization(
            resource, ns,
            lambda: self.master.registry.patch(resource, ns, name, patch, admit=admit),
        )
        if resource == "customresourcedefinitions":
            self.master.remove_crd(old)
            self.master.apply_crd(updated)
        elif resource == "apiservices":
            self.master.remove_apiservice(old)
            self.master.apply_apiservice(updated)
        self.master.audit("patch", resource, ns, name, self._user.name,
                          request_obj=patch,
                          response_obj=lambda: self.master.scheme.encode(updated))
        self._send_obj(200, updated)

    # --------------------------------------------------------------- DELETE

    def _do_delete(self, resource, ns, name, sub, q):
        if not name:
            raise BadRequest("collection delete not supported; delete by name")
        grace = q.get("gracePeriodSeconds")
        obj = self.master.registry.delete(
            resource, ns, name, None if grace is None else int(grace)
        )
        self.master.audit("delete", resource, ns, name, self._user.name)
        if resource == "customresourcedefinitions":
            self.master.remove_crd(obj)
        elif resource == "apiservices":
            self.master.remove_apiservice(obj)
        self._send_obj(200, obj)


class Metrics:
    """Minimal Prometheus-style counters/histogram sums (ref: apiserver
    request metrics; full component metrics live in utils/metrics.py)."""

    def __init__(self):
        self._lock = locksan.make_lock("apiserver.Metrics._lock")
        self._counts: Dict[str, int] = {}
        self._sums: Dict[str, float] = {}

    def observe(self, method: str, resource: str, seconds: float):
        key = f'method="{method}",resource="{resource}"'
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._sums[key] = self._sums.get(key, 0.0) + seconds

    def render(self) -> str:
        lines = [
            "# TYPE apiserver_request_total counter",
        ]
        with self._lock:
            for key, n in sorted(self._counts.items()):
                lines.append(f"apiserver_request_total{{{key}}} {n}")
            lines.append("# TYPE apiserver_request_duration_seconds_sum counter")
            for key, s in sorted(self._sums.items()):
                lines.append(f"apiserver_request_duration_seconds_sum{{{key}}} {s:.6f}")
        return "\n".join(lines) + "\n"


class Master:
    """In-process apiserver: store + registry + admission + HTTP frontend.

    Instantiating a Master installs the fast header parser
    (utils/fasthttp.py — header parsing was ~18% of a pod-create
    roundtrip through email.parser).  Installed at construction, not at
    import: merely importing this module must not repoint stdlib
    behavior for unrelated code in the process."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        scheme: Optional[Scheme] = None,
        wal_path: Optional[str] = None,
        token: str = "",
        audit_log: Optional[list] = None,
        audit_path: Optional[str] = None,
        authorization_mode: str = "AlwaysAllow",  # AlwaysAllow | "Node,RBAC"
        static_tokens: Optional[Dict[str, tuple]] = None,
        sa_signing_key: str = "ktpu-sa-key",
        ca_key: str = "ktpu-ca-key",
        admission_plugins: Optional[List[str]] = None,  # extra opt-ins, e.g. AlwaysPullImages
        authentication_webhook_url: str = "",  # TokenReview callout (webhook authn)
        oidc_issuer: str = "",                 # OIDC-style JWT authn (HS256)
        oidc_client_id: str = "",
        oidc_hs256_key: str = "",
        oidc_username_claim: str = "sub",
        oidc_groups_claim: str = "groups",
        audit_policy: Optional[dict] = None,   # audit policy doc (levels/rules)
        audit_webhook_url: str = "",           # batching audit sink
        tls_cert_file: str = "",               # serve HTTPS (ref serve.go)
        tls_key_file: str = "",
        client_ca_file: str = "",              # verify client certs (x509 authn)
        store_address: str = "",               # external StoreServer (etcd role):
                                               # unix path or host:port — makes
                                               # this apiserver stateless.
                                               # ';'-separated groups = one
                                               # SHARD each (each group its own
                                               # comma-separated primary,standby
                                               # failover list) — the sharded
                                               # store set (storage/shardmap.py)
        store_shards: int = 1,                 # in-process store shard count
                                               # (>1 partitions /registry/ by
                                               # key hash: per-shard WAL/commit
                                               # queue/watch ring; ignored with
                                               # store_address — remote shard
                                               # count comes from the ';' list)
        store_ca_file: str = "",               # verify the store's TLS cert
        store_codec: str = "json",             # store-wire codec (--wire-codec):
                                               # negotiated at dial, falls back
                                               # to newline-JSON on old stores
        watch_queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT,  # per-watcher
                                               # event bound before slow-
                                               # consumer eviction (410)
        cacher_history_limit: Optional[int] = None,  # watch-cache resume
                                               # window (events); None =
                                               # storage/cacher default.
                                               # Tests/chaos shrink it to
                                               # force compaction quickly
                                               # (the idle-informer
                                               # bookmark regression)
        store_history_limit: Optional[int] = None,  # in-process store
                                               # resume ring (events);
                                               # shrink ALONGSIDE the
                                               # cacher window or the
                                               # store-fallback watch
                                               # path absorbs the
                                               # compaction being tested
        write_coalesce_window: float = 0.0,    # seconds; opt-in singleton
                                               # write coalescing under
                                               # burst (see _WriteCoalescer)
        wal_sync: str = "batch",               # WAL fsync policy
                                               # (none|batch|always)
        max_inflight_mutating: int = 256,      # overload shedding: mutating
                                               # requests past this bound
                                               # get 429 + Retry-After
                                               # (0 disables; reads are
                                               # never shed)
        store=None,                            # pre-built store OBJECT
                                               # shared by several in-
                                               # process Masters (the
                                               # LocalCluster apiservers=N
                                               # shape); the caller owns
                                               # its lifecycle — stop()
                                               # won't close it
        render_client_metrics: bool = True,    # render process-global
                                               # client metrics (retries,
                                               # informer family, gang
                                               # counters) on /metrics —
                                               # exactly ONE Master per
                                               # process should, or a
                                               # fleet merge double-counts
        render_store_metrics: Optional[bool] = None,  # render the store's
                                               # commit/WAL block — None =
                                               # only when this Master
                                               # owns the store (a shared
                                               # store's numbers must
                                               # appear on ONE /metrics)
        event_loop_serving: Optional[bool] = None,  # watch streams on the
                                               # shared dispatcher (one
                                               # thread for all of them)
                                               # vs a parked handler
                                               # thread each; None = env
                                               # KTPU_EVENTLOOP (default
                                               # on, "0"/"false" off —
                                               # the A/B knob)
    ):
        fasthttp.install()  # idempotent (see class docstring)
        # own copy: CRD registrations must not leak into the process-global
        # scheme shared by every other Master/client in this process
        self.scheme = scheme or global_scheme.copy()
        self.store_is_remote = bool(store_address) and store is None
        self._owns_store = store is None
        if store_history_limit is not None and (
                store is not None or store_address or store_shards > 1):
            # the knob exists to force REAL compaction in tests/chaos;
            # silently ignoring it for sharded/remote/injected stores
            # would let the idle-informer bookmark regression pass
            # against an uncompacted store-fallback watch path
            raise ValueError(
                "store_history_limit applies only to the plain in-process "
                "store (not store=, store_address, or store_shards>1); "
                "shrink those stores' rings at construction instead")
        self.render_client_metrics = render_client_metrics
        if store is not None:
            # shared in-process store (LocalCluster multi-apiserver):
            # this Master layers its own cacher/registry over it; the
            # sharded facade reports its arity via .shards
            self.store = store
            self.store_shards = getattr(store, "shards", 1)
        elif store_address:
            from ..storage.remote import RemoteStore

            # ';'-separated shard groups; within each group, comma-
            # separated primary,standby — RemoteStore parses the group
            # and fails over inside it (storage/remote.py).  Multiple
            # groups build the sharded facade: one RemoteStore per shard
            # on its own `store.shard.*` faultline sites.
            groups = parse_shard_addresses(store_address)
            if len(groups) > 1:
                self.store = ShardedStore([
                    RemoteStore(self.scheme, g, ca_file=store_ca_file,
                                codec=store_codec,
                                site_prefix="store.shard")
                    for g in groups
                ])
            else:
                self.store = RemoteStore(self.scheme, store_address,
                                         ca_file=store_ca_file,
                                         codec=store_codec)
            self.store_shards = len(groups)
        elif store_shards > 1:
            # in-process sharded store: per-shard WAL/commit queue/watch
            # ring/serialization-cache feed, stride-encoded revisions
            self.store = build_sharded_store(
                self.scheme.copy, store_shards,
                wal_path=wal_path, wal_sync=wal_sync)
            self.store_shards = store_shards
        else:
            store_kw = {}
            if store_history_limit is not None:
                store_kw["history_limit"] = store_history_limit
            self.store = Store(self.scheme, wal_path=wal_path,
                               wal_sync=wal_sync, **store_kw)
            self.store_shards = 1
        self.render_store_metrics = (self._owns_store
                                     if render_store_metrics is None
                                     else render_store_metrics)
        self.write_coalescer = _WriteCoalescer(write_coalesce_window)
        self.inflight = _InflightLimiter(max_inflight_mutating)
        self.registry = Registry(self.store, self.scheme)
        # k8s-cacher-analog read layer: GET/LIST/WATCH serve from an
        # in-memory watch-fed view (one store watch and zero decode/encode
        # per request); writes keep going straight to the store.  Paired
        # with scheme.serialization_cache, encode work per event is O(1)
        # in watcher count.
        self.watch_queue_limit = watch_queue_limit
        cacher_kw = {}
        if cacher_history_limit is not None:
            cacher_kw["history_limit"] = cacher_history_limit
        if isinstance(self.store, ShardedStore):
            # per-shard caches: each shard's view is fed (and kept fresh)
            # independently; reads merge, watches fan into one queue
            self.cacher = ShardedCacher(self.store, self.scheme,
                                        queue_limit=watch_queue_limit,
                                        **cacher_kw).start()
        else:
            self.cacher = Cacher(self.store, self.scheme,
                                 queue_limit=watch_queue_limit,
                                 **cacher_kw).start()
        # progress/composite/lag BOOKMARK frames emitted by this
        # apiserver's watch streams (the idle-informer freshness surface)
        self._watch_bookmarks = 0
        self._bookmark_lock = locksan.make_lock("Master._bookmark_lock")
        self.token = token
        self.metrics = Metrics()
        # request spans land here, served at /debug/traces (utils/spans).
        # Sized for the write rate: a ring buffer of the newest mutations
        # (heartbeat status PUTs included), not a durable trace store —
        # scrape or query promptly after the incident window.
        self.spans = spanlib.SpanCollector("apiserver", capacity=4096)
        self.quota_lock = locksan.make_lock("Master.quota_lock")
        self.stopping = threading.Event()
        if event_loop_serving is None:
            event_loop_serving = os.environ.get(
                "KTPU_EVENTLOOP", "1").lower() not in ("0", "false")
        self.event_loop_serving = event_loop_serving
        # handed-off watch connections owned by the dispatcher (so stop()
        # can end every stream); the dispatcher itself is lazy — a master
        # that never serves a watch never starts it
        self._watch_conns: set = set()
        self._watch_conns_lock = locksan.make_lock(
            "Master._watch_conns_lock")
        self._audit_log = audit_log
        self._audit_path = audit_path
        self._audit_lock = locksan.make_lock("Master._audit_lock")
        from .audit import AuditPolicy, WebhookAuditBackend

        self.audit_policy = AuditPolicy.from_dict(audit_policy)
        self._audit_webhook = (WebhookAuditBackend(audit_webhook_url)
                               if audit_webhook_url else None)
        self._apiservice_index: Dict[tuple, str] = {}  # (group, version) -> name
        # one generation-stamped ~1s TTL cache per hot admission input
        # (webhook configs / pod presets / pod security policies) — the
        # SAME idiom everywhere so write-through invalidation can't race
        # a stale scan back in (see _AdmissionTTLCache)
        self._webhook_cache = _AdmissionTTLCache()    # key: resource
        self._podpreset_cache = _AdmissionTTLCache()  # key: namespace
        self._psp_cache = _AdmissionTTLCache()        # key: ""
        self.authorization_mode = authorization_mode
        tokens = dict(static_tokens or {})
        if token:
            tokens[token] = ("system:admin", [GROUP_MASTERS])
        authns = [
            StaticTokenAuthenticator(tokens),
            ServiceAccountAuthenticator(
                sa_signing_key, get_serviceaccount=self._get_serviceaccount
            ),
            CertificateAuthenticator(ca_key),
            BootstrapTokenAuthenticator(self._get_secret_or_none),
        ]
        if oidc_issuer:
            # OIDCAuthenticator itself refuses an empty key; surface the
            # misconfiguration at construction, not first request
            authns.append(OIDCAuthenticator(
                oidc_issuer, oidc_client_id, oidc_hs256_key,
                username_claim=oidc_username_claim,
                groups_claim=oidc_groups_claim))
        if authentication_webhook_url:
            # last: local authenticators win, unknown tokens go remote
            authns.append(WebhookTokenAuthenticator(authentication_webhook_url))
        self.authenticators = AuthenticatorChain(authns)
        if authorization_mode == "AlwaysAllow":
            self.authorizer = AuthorizerChain([AlwaysAllowAuthorizer()])
        else:
            chain = []
            for mode in authorization_mode.split(","):
                mode = mode.strip()
                if mode == "Node":
                    chain.append(
                        NodeAuthorizer(self._get_pod_or_none, self._list_all_pods,
                                       get_serviceaccount=self._get_serviceaccount)
                    )
                elif mode == "RBAC":
                    chain.append(RBACAuthorizer(self._list_for_auth))
                elif mode == "AlwaysAllow":
                    chain.append(AlwaysAllowAuthorizer())
            self.authorizer = AuthorizerChain(chain)
        plugins = [
            NamespaceAutoProvision(self.registry.ensure_namespace),
            NodeRestriction(),  # before SA defaulting: checks the raw spec
            PodNodeSelector(self._get_namespace_or_none),
            PriorityResolver(self._get_priority_class),
            ExtendedResourceToleration(),  # before ResourceV2: sees raw limits too
            DefaultTolerationSeconds(),
            ResourceV2(),
            GangDefaulter(),
            ServiceAccountAdmission(),
            PodPresetAdmission(self._list_podpresets),
            IdentityStamp(),
            # dynamic admission: mutating webhooks run after the built-in
            # mutators (they see the rewritten object) and before the
            # validating phase; validating webhooks run dead last
            MutatingWebhookAdmission(
                lambda: self._list_webhook_configs("mutatingwebhookconfigurations")),
            LimitRanger(self._list_limit_ranges),
            ResourceQuotaAdmission(self._list_quotas, self._quota_usage),
            PodSecurityPolicyAdmission(self._list_psps),
            EventRateLimit(),
            ValidatingWebhookAdmission(
                lambda: self._list_webhook_configs("validatingwebhookconfigurations")),
        ]
        # opt-in plugins by name (the --admission-control list analog)
        for name in (admission_plugins or []):
            if name == "AlwaysPullImages":
                plugins.append(AlwaysPullImages())
            else:
                raise ValueError(f"unknown admission plugin {name!r}")
        self.admission = AdmissionChain(plugins)
        self._httpd = _ApiHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.master = self  # type: ignore[attr-defined]
        from ..utils.streams import quiet_connection_errors

        quiet_connection_errors(self._httpd)
        self.host, self.port = self._httpd.server_address[:2]
        self.client_ca_file = client_ca_file
        self._kubelet_client_ctx = None  # built lazily, shared (immutable CA)
        if tls_cert_file:
            # HTTPS-only: there is no plaintext fallback listener (ref
            # apiserver/pkg/server/serve.go — the secure port is the port)
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert_file,
                                keyfile=tls_key_file or None)
            if client_ca_file:
                ctx.load_verify_locations(cafile=client_ca_file)
                # OPTIONAL: bearer-token clients (bootstrap tokens, SA
                # tokens) handshake without a cert; x509 clients get
                # verified and mapped in _peer_cert_user
                ctx.verify_mode = ssl.CERT_OPTIONAL
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            self.url = f"https://{self.host}:{self.port}"
        else:
            self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def dispatcher(self) -> _eventloop.EventLoop:
        """The shared event loop watch connections are handed off to
        (started on first use — see utils/eventloop.shared_loop)."""
        return _eventloop.shared_loop()

    def adopt_watch_conn(self, conn: "_WatchConn"):
        """Take ownership of a handed-off watch connection: track it for
        stop() and schedule its registration on the loop thread."""
        with self._watch_conns_lock:
            self._watch_conns.add(conn)
        self.dispatcher().call_soon(conn.start)

    def _drop_watch_conn(self, conn: "_WatchConn"):
        with self._watch_conns_lock:
            self._watch_conns.discard(conn)

    def note_watch_bookmark(self):
        """Count one emitted BOOKMARK frame (composite, lag-stamp, or
        progress) — ktpu_watch_bookmarks_total on /metrics."""
        with self._bookmark_lock:
            self._watch_bookmarks += 1

    @property
    def watch_bookmarks(self) -> int:
        with self._bookmark_lock:
            return self._watch_bookmarks

    def _get_priority_class(self, name: str):
        return self.store.get_or_none(self.registry.key("priorityclasses", "", name))

    def _list_podpresets(self, namespace: str):
        return self._podpreset_cache.get(
            namespace,
            lambda: self.store.list(
                self.registry.prefix("podpresets", namespace))[0])

    def _list_psps(self):
        return self._psp_cache.get(
            "", lambda: self.store.list(self.registry.prefix(
                "podsecuritypolicies", ""))[0])

    def _list_webhook_configs(self, resource: str):
        """Webhook configs for the admission chain, cached ~1s (see
        _AdmissionTTLCache): admission runs on EVERY write and a store
        scan per write is pure overhead on webhook-free clusters
        (upstream reads these through an informer with comparable
        staleness).

        Re-entrancy note: webhook callouts can run while the quota lock is
        held (_with_quota_serialization); a webhook handler that writes a
        quota-counted object back into THIS apiserver blocks on that lock
        until the callout times out — bounded by timeout_seconds, same
        hazard class as upstream's re-entrant webhook writes."""
        return self._webhook_cache.get(
            resource,
            lambda: self.store.list(self.registry.prefix(resource, ""))[0])

    def _get_namespace_or_none(self, name: str):
        if not name:
            return None
        return self.store.get_or_none(self.registry.key("namespaces", "", name))

    def _list_limit_ranges(self, namespace: str):
        items, _ = self.store.list(self.registry.prefix("limitranges", namespace))
        return items

    def _list_quotas(self, namespace: str):
        items, _ = self.store.list(self.registry.prefix("resourcequotas", namespace))
        return items

    def _quota_usage(self, namespace: str):
        return compute_namespace_usage(
            lambda resource, ns: self.store.list(self.registry.prefix(resource, ns))[0],
            namespace,
        )

    def _get_serviceaccount(self, namespace: str, name: str):
        if not namespace or not name:
            return None
        return self.store.get_or_none(
            self.registry.key("serviceaccounts", namespace, name)
        )

    def _get_secret_or_none(self, namespace: str, name: str):
        if not namespace or not name:
            return None
        return self.store.get_or_none(self.registry.key("secrets", namespace, name))

    def _get_pod_or_none(self, namespace: str, name: str):
        if not namespace or not name:
            return None
        return self.store.get_or_none(self.registry.key("pods", namespace, name))

    def _list_all_pods(self):
        items, _ = self.store.list(self.registry.prefix("pods"))
        return items

    def _list_for_auth(self, resource: str, namespace: str):
        items, _ = self.store.list(self.registry.prefix(resource, namespace))
        return items

    # -------------------------------------------------- CRDs and aggregation

    def apply_crd(self, crd: t.CustomResourceDefinition):
        """Serve the custom resource immediately (ref: apiextensions-apiserver
        customresource_handler)."""
        self.scheme.register_dynamic(
            kind=crd.spec.names.kind,
            plural=crd.spec.names.plural,
            api_version=f"{crd.spec.group}/{crd.spec.version}",
            namespaced=crd.spec.scope == "Namespaced",
        )

    def remove_crd(self, crd: t.CustomResourceDefinition):
        self.scheme.deregister_dynamic(crd.spec.names.kind)

    def _restore_crds(self):
        """Re-register dynamic kinds + the APIService index after a WAL
        restart."""
        items, _ = self.store.list(self.registry.prefix("customresourcedefinitions"))
        for crd in items:
            self.apply_crd(crd)
        items, _ = self.store.list(self.registry.prefix("apiservices"))
        for svc in items:
            self.apply_apiservice(svc)

    def apply_apiservice(self, svc: t.APIService):
        if svc.spec.service_name:
            self._apiservice_index[(svc.spec.group, svc.spec.version)] = (
                svc.metadata.name
            )

    def remove_apiservice(self, svc: t.APIService):
        self._apiservice_index.pop((svc.spec.group, svc.spec.version), None)

    def find_apiservice(self, group: str, version: str):
        """O(1) on the hot dispatch path — every /apis/* request asks."""
        name = self._apiservice_index.get((group, version))
        if name is None:
            return None
        svc = self.store.get_or_none(self.registry.key("apiservices", "", name))
        if svc is None or not svc.spec.service_name:
            return None
        return svc

    def resolve_service_endpoint(self, namespace: str, name: str, port: int):
        """First ready endpoint address of a service (host, port). The
        APIService's requested port wins when the subset advertises it; a
        single advertised port is taken as the translated target port."""
        eps = self.store.get_or_none(
            self.registry.key("endpoints", namespace or "default", name)
        )
        if eps is None:
            return None
        for subset in eps.subsets:
            for addr in subset.addresses:
                advertised = [p.port for p in subset.ports if p.port]
                if port in advertised:
                    return addr.ip, port
                if len(advertised) == 1:
                    return addr.ip, advertised[0]
                return addr.ip, port
        return None

    def audit(self, verb: str, resource: str, ns: str, name: str,
              user: str = "", request_obj=None, response_obj=None):
        """Advanced audit (ref: apiserver/pkg/audit + plugin/pkg/audit):
        the policy decides the level per request (None drops it; Request /
        RequestResponse capture object payloads); entries flow to the
        in-memory sink, the JSONL file, and the batching webhook."""
        if (self._audit_log is None and self._audit_path is None
                and self._audit_webhook is None):
            return
        from .audit import LEVEL_NONE, LEVEL_REQUEST_RESPONSE, build_entry

        level = self.audit_policy.level_for(user, verb, resource, ns)
        if level == LEVEL_NONE:
            return
        if callable(response_obj):
            # lazily materialized: the hot write path must not pay a second
            # full encode unless this request's level actually captures it
            response_obj = (response_obj()
                            if level == LEVEL_REQUEST_RESPONSE else None)
        entry = build_entry(level, user, verb, resource, ns, name,
                            request_obj=request_obj,
                            response_obj=response_obj)
        if self._audit_log is not None:
            self._audit_log.append(entry)
        if self._audit_path is not None:
            with self._audit_lock:
                with open(self._audit_path, "a") as f:
                    f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        if self._audit_webhook is not None:
            self._audit_webhook.add(entry)

    def start(self) -> "Master":
        from ..utils.gctune import tune_for_server

        tune_for_server()
        self.registry.ensure_namespace("default")
        self.registry.ensure_namespace("kube-system")
        self._restore_crds()
        self._thread = threading.Thread(  # ktpulint: ignore[KTPU015] the single serve_forever acceptor thread — handler threads return after handoff, it is not per-connection
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1}, daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self.stopping.set()
        # cacher first: its pump is a store watcher, and open client
        # watches must see their streams end before the store closes
        self.cacher.stop()
        # handed-off streams: end each on the loop thread (terminal chunk
        # + close once the bytes drain) — the dispatcher itself is shared
        # and stays up
        with self._watch_conns_lock:
            conns = list(self._watch_conns)
        if conns:
            loop = self.dispatcher()
            for conn in conns:
                loop.call_soon(conn.shutdown)
        self._httpd.shutdown()
        self._httpd.server_close()
        # audit sink last: in-flight requests finishing during shutdown
        # still audit, and the final flush must include them
        if self._audit_webhook is not None:
            self._audit_webhook.stop()
        if self._owns_store:
            # a shared store (Master(store=...)) outlives this apiserver:
            # its owner — the LocalCluster — closes it once, after every
            # Master over it has stopped
            self.store.close()
