"""HTTP API server: REST + streaming watch over the registry.

Ref: cmd/kube-apiserver + staging/src/k8s.io/apiserver/pkg/server — the
filter chain (authn -> audit -> authz -> admission) collapses here to a
bearer-token check hook, an audit log hook, and the admission chain; the
wire protocol is the reference's: JSON objects, list kinds with a
resourceVersion for watch resume, and watch streams as line-delimited
{"type","object"} frames over chunked HTTP (exactly what client-go's
reflector consumes).

The in-process `Master` is the master_utils.RunAMaster equivalent
(test/integration/framework/master_utils.go:193): tests and the local
cluster boot embed a full apiserver over the MVCC store with zero setup.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from ..api import types as t
from ..machinery import ApiError, BadRequest, NotFound
from ..machinery.scheme import Scheme, global_scheme
from ..storage import Store
from .admission import (
    CREATE,
    UPDATE,
    AdmissionChain,
    EventRateLimit,
    GangDefaulter,
    LimitRanger,
    NamespaceAutoProvision,
    PriorityResolver,
    ResourceQuotaAdmission,
    ResourceV2,
    ServiceAccountAdmission,
    compute_namespace_usage,
)
from .registry import Registry

WATCH_HEARTBEAT_SECONDS = 5.0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ktpu-apiserver/0.1"

    # quiet request logging; audit hook covers observability
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    # ------------------------------------------------------------- plumbing

    @property
    def master(self) -> "Master":
        return self.server.master  # type: ignore[attr-defined]

    def _send_json(self, code: int, payload: Dict[str, Any]):
        raw = json.dumps(payload, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send_error(self, err: ApiError):
        self._send_json(err.code, err.to_status())

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            raise BadRequest("request body required")
        try:
            return json.loads(self.rfile.read(length))
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON body: {e}") from e

    def _authn(self) -> bool:
        token = self.master.token
        if not token:
            return True
        auth = self.headers.get("Authorization", "")
        return auth == f"Bearer {token}"

    # ------------------------------------------------------------- dispatch

    def _route(self):
        parsed = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        parts = [p for p in parsed.path.split("/") if p]
        return parts, q

    def _parse_resource_path(self, parts):
        """Return (resource, namespace, name, subresource).

        Accepted forms (group prefixes /api/v1 and /apis/<g>/<v> both map to
        the single flat registry):
          <prefix>/<resource>
          <prefix>/<resource>/<name>[/<sub>]
          <prefix>/namespaces/<ns>/<resource>[/<name>[/<sub>]]
        """
        if not parts or parts[0] not in ("api", "apis"):
            raise NotFound(f"unknown path {self.path}")
        rest = parts[2:] if parts[0] == "api" else parts[3:]
        if not rest:
            raise NotFound("missing resource")
        # /namespaces/<ns>/<resource>... is the namespaced form only when
        # <resource> is actually a registered resource — otherwise it's the
        # cluster-scoped namespaces object's own subresource
        # (/namespaces/<name>/status).
        if (
            rest[0] == "namespaces"
            and len(rest) >= 3
            and rest[2] in self.master.scheme.by_resource
        ):
            ns, resource = rest[1], rest[2]
            name = rest[3] if len(rest) > 3 else ""
            sub = rest[4] if len(rest) > 4 else ""
            return resource, ns, name, sub
        resource = rest[0]
        name = rest[1] if len(rest) > 1 else ""
        sub = rest[2] if len(rest) > 2 else ""
        return resource, "", name, sub

    def _handle(self, method: str):
        start = time.monotonic()
        try:
            if not self._authn():
                self.send_response(401)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            parts, q = self._route()
            if parts and parts[0] in ("healthz", "readyz", "livez"):
                self._send_json(200, {"status": "ok"})
                return
            if parts and parts[0] == "version":
                self._send_json(200, {"gitVersion": "v0.1.0-ktpu", "platform": "tpu"})
                return
            if parts and parts[0] == "metrics":
                self._serve_metrics()
                return
            resource, ns, name, sub = self._parse_resource_path(parts)
            if resource not in self.master.scheme.by_resource:
                raise NotFound(f"resource {resource!r} not registered")
            handler = getattr(self, f"_do_{method.lower()}")
            handler(resource, ns, name, sub, q)
            self.master.metrics.observe(method, resource, time.monotonic() - start)
        except ApiError as e:
            try:
                self._send_error(e)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            traceback.print_exc()
            try:
                err = ApiError(str(e))
                self._send_error(err)
            except Exception:  # noqa: BLE001
                pass

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_PUT(self):
        self._handle("PUT")

    def do_PATCH(self):
        self._handle("PATCH")

    def do_DELETE(self):
        self._handle("DELETE")

    # ------------------------------------------------------------------ GET

    def _do_get(self, resource, ns, name, sub, q):
        reg = self.master.registry
        if name and not sub:
            obj = reg.get(resource, ns, name)
            self._send_json(200, self.master.scheme.encode(obj))
            return
        if name and sub:
            raise NotFound(f"subresource {sub!r} not readable")
        if q.get("watch") in ("1", "true"):
            self._serve_watch(resource, ns, q)
            return
        items, rev = reg.list(
            resource,
            ns,
            label_selector=q.get("labelSelector", ""),
            field_selector=q.get("fieldSelector", ""),
        )
        kind = self.master.scheme.by_resource[resource].KIND + "List"
        self._send_json(
            200,
            {
                "kind": kind,
                "apiVersion": "v1",
                "metadata": {"resourceVersion": str(rev)},
                "items": [self.master.scheme.encode(o) for o in items],
            },
        )

    def _serve_watch(self, resource, ns, q):
        since = int(q.get("resourceVersion") or 0)
        timeout = float(q.get("timeoutSeconds") or 0)
        w = self.master.registry.watch(
            resource,
            ns,
            since_rev=since,
            label_selector=q.get("labelSelector", ""),
            field_selector=q.get("fieldSelector", ""),
        )
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        deadline = time.monotonic() + timeout if timeout else None
        try:
            while True:
                if deadline and time.monotonic() >= deadline:
                    break
                ev = w.next_timeout(WATCH_HEARTBEAT_SECONDS)
                if self.master.stopping.is_set():
                    break
                if ev is None:
                    # heartbeat chunk keeps half-open connections detectable
                    self._write_chunk(b"")
                    continue
                if not w.event_matches(ev.object):
                    continue
                frame = json.dumps(
                    {"type": ev.type, "object": ev.object}, separators=(",", ":")
                ).encode() + b"\n"
                self._write_chunk(frame)
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            pass
        finally:
            w.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except Exception:  # noqa: BLE001
                pass
            self.close_connection = True

    def _write_chunk(self, data: bytes):
        if not data:
            # zero-length would terminate chunked encoding; send a newline
            data = b"\n"
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _serve_metrics(self):
        body = self.master.metrics.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ----------------------------------------------------------------- POST

    def _do_post(self, resource, ns, name, sub, q):
        reg = self.master.registry
        body = self._read_body()
        if resource == "pods" and sub == "binding":
            binding = self.master.scheme.decode(body)
            pod = reg.bind(ns, name, binding)
            self.master.audit("bind", resource, ns, name)
            self._send_json(201, self.master.scheme.encode(pod))
            return
        if sub:
            raise NotFound(f"subresource {sub!r} not writable")
        obj = self.master.scheme.decode(body)
        # default namespace from the URL before admission so plugins
        # (NamespaceAutoProvision) see the effective namespace
        if ns and not obj.metadata.namespace:
            obj.metadata.namespace = ns
        # Quota-counted resources serialize admission-check + commit so two
        # concurrent creates cannot both pass a nearly-exhausted quota
        # (admission computes usage from the store; unserialized it's TOCTOU).
        effective_ns = ns or obj.metadata.namespace or "default"
        if resource in ResourceQuotaAdmission.COUNTED and self.master._list_quotas(
            effective_ns
        ):
            with self.master.quota_lock:
                obj = self.master.admission.admit(CREATE, resource, obj)
                created = reg.create(resource, ns, obj)
        else:
            obj = self.master.admission.admit(CREATE, resource, obj)
            created = reg.create(resource, ns, obj)
        self.master.audit("create", resource, ns, created.metadata.name)
        self._send_json(201, self.master.scheme.encode(created))

    # ------------------------------------------------------------------ PUT

    def _do_put(self, resource, ns, name, sub, q):
        reg = self.master.registry
        body = self._read_body()
        obj = self.master.scheme.decode(body)
        if sub == "status":
            updated = reg.update_status(resource, ns, name, obj)
        elif sub:
            raise NotFound(f"subresource {sub!r} not writable")
        else:
            old = reg.get(resource, ns, name)
            obj = self.master.admission.admit(UPDATE, resource, obj, old)
            updated = reg.update(resource, ns, name, obj)
        self.master.audit("update", resource, ns, name)
        self._send_json(200, self.master.scheme.encode(updated))

    # ---------------------------------------------------------------- PATCH

    def _do_patch(self, resource, ns, name, sub, q):
        patch = self._read_body()
        if sub == "status":
            patch = {"status": patch.get("status", patch)}
        updated = self.master.registry.patch(resource, ns, name, patch)
        self.master.audit("patch", resource, ns, name)
        self._send_json(200, self.master.scheme.encode(updated))

    # --------------------------------------------------------------- DELETE

    def _do_delete(self, resource, ns, name, sub, q):
        if not name:
            raise BadRequest("collection delete not supported; delete by name")
        grace = q.get("gracePeriodSeconds")
        obj = self.master.registry.delete(
            resource, ns, name, None if grace is None else int(grace)
        )
        self.master.audit("delete", resource, ns, name)
        self._send_json(200, self.master.scheme.encode(obj))


class Metrics:
    """Minimal Prometheus-style counters/histogram sums (ref: apiserver
    request metrics; full component metrics live in utils/metrics.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._sums: Dict[str, float] = {}

    def observe(self, method: str, resource: str, seconds: float):
        key = f'method="{method}",resource="{resource}"'
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._sums[key] = self._sums.get(key, 0.0) + seconds

    def render(self) -> str:
        lines = [
            "# TYPE apiserver_request_total counter",
        ]
        with self._lock:
            for key, n in sorted(self._counts.items()):
                lines.append(f"apiserver_request_total{{{key}}} {n}")
            lines.append("# TYPE apiserver_request_duration_seconds_sum counter")
            for key, s in sorted(self._sums.items()):
                lines.append(f"apiserver_request_duration_seconds_sum{{{key}}} {s:.6f}")
        return "\n".join(lines) + "\n"


class Master:
    """In-process apiserver: store + registry + admission + HTTP frontend."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        scheme: Optional[Scheme] = None,
        wal_path: Optional[str] = None,
        token: str = "",
        audit_log: Optional[list] = None,
    ):
        self.scheme = scheme or global_scheme
        self.store = Store(self.scheme, wal_path=wal_path)
        self.registry = Registry(self.store, self.scheme)
        self.token = token
        self.metrics = Metrics()
        self.quota_lock = threading.Lock()
        self.stopping = threading.Event()
        self._audit_log = audit_log
        self.admission = AdmissionChain(
            [
                NamespaceAutoProvision(self.registry.ensure_namespace),
                PriorityResolver(self._get_priority_class),
                ResourceV2(),
                GangDefaulter(),
                ServiceAccountAdmission(),
                LimitRanger(self._list_limit_ranges),
                ResourceQuotaAdmission(self._list_quotas, self._quota_usage),
                EventRateLimit(),
            ]
        )
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.master = self  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def _get_priority_class(self, name: str):
        return self.store.get_or_none(self.registry.key("priorityclasses", "", name))

    def _list_limit_ranges(self, namespace: str):
        items, _ = self.store.list(self.registry.prefix("limitranges", namespace))
        return items

    def _list_quotas(self, namespace: str):
        items, _ = self.store.list(self.registry.prefix("resourcequotas", namespace))
        return items

    def _quota_usage(self, namespace: str):
        return compute_namespace_usage(
            lambda resource, ns: self.store.list(self.registry.prefix(resource, ns))[0],
            namespace,
        )

    def audit(self, verb: str, resource: str, ns: str, name: str):
        if self._audit_log is not None:
            self._audit_log.append(
                {"ts": time.time(), "verb": verb, "resource": resource, "ns": ns, "name": name}
            )

    def start(self) -> "Master":
        self.registry.ensure_namespace("default")
        self.registry.ensure_namespace("kube-system")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1}, daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self.stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.store.close()
