"""Container runtime: the CRI seam (ref: pkg/kubelet/apis/cri/v1alpha1/
runtime/api.proto RuntimeService/ImageService, dockershim server,
pkg/kubelet/remote client).

Two implementations, both behind the same interface the kubelet consumes:

- ProcessRuntime — containers are host subprocesses.  This is the
  TPU-native answer for this environment (no dockerd in the image): the
  "image" is advisory, the command runs directly with the ContainerSpec's
  injected env (TPU_VISIBLE_CHIPS etc.), logs stream to per-container
  files.  A real JAX training process on the real TPU chip runs this way.
- FakeRuntime — the kubemark hollow runtime (ref: pkg/kubemark/
  hollow_kubelet.go + libdocker/fake_client.go): containers are in-memory
  records with scriptable exit behavior, enabling 1000-node scale tests
  with zero real processes.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional
from ..utils import locksan

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

CONTAINER_CREATED = "CREATED"
CONTAINER_RUNNING = "RUNNING"
CONTAINER_EXITED = "EXITED"


@dataclass
class SandboxRecord:
    id: str
    pod_name: str
    pod_namespace: str
    pod_uid: str
    state: str = SANDBOX_READY
    created_at: float = field(default_factory=time.time)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class ContainerConfig:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    working_dir: str = ""
    devices: List[dict] = field(default_factory=list)
    mounts: List[dict] = field(default_factory=list)
    annotations: Dict[str, str] = field(default_factory=dict)
    # cgroup.procs files the starting process must join (the CRI
    # cgroup_parent analog; empty = no cgroup enforcement)
    cgroup_procs_files: List[str] = field(default_factory=list)
    # logical cpus the process tree is pinned to (CPU manager static policy;
    # empty = no pinning)
    cpuset: List[int] = field(default_factory=list)
    # effective security context (ref pkg/securitycontext): the runtime
    # drops to this uid/gid before exec; None = run as the kubelet's user
    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None
    privileged: bool = False


@dataclass
class ContainerRecord:
    id: str
    sandbox_id: str
    name: str
    image: str
    state: str = CONTAINER_CREATED
    exit_code: Optional[int] = None
    started_at: float = 0.0
    finished_at: float = 0.0
    restart_count: int = 0
    log_path: str = ""


class RuntimeService:
    """The interface the kubelet drives (20-RPC RuntimeService condensed to
    the calls the sync loop actually needs)."""

    # identity a container with no runAsUser execs as; None = unknown
    # (the kubelet's runAsNonRoot verification fails closed on None)
    default_uid: "Optional[int]" = None

    def version(self) -> str:
        raise NotImplementedError

    def run_pod_sandbox(self, pod_name, pod_namespace, pod_uid, labels=None) -> str:
        raise NotImplementedError

    def stop_pod_sandbox(self, sandbox_id: str):
        raise NotImplementedError

    def remove_pod_sandbox(self, sandbox_id: str):
        raise NotImplementedError

    def list_pod_sandboxes(self) -> List[SandboxRecord]:
        raise NotImplementedError

    def create_container(self, sandbox_id: str, config: ContainerConfig) -> str:
        raise NotImplementedError

    def start_container(self, container_id: str):
        raise NotImplementedError

    def stop_container(self, container_id: str, timeout: float = 10.0):
        raise NotImplementedError

    def remove_container(self, container_id: str):
        raise NotImplementedError

    def list_containers(self) -> List[ContainerRecord]:
        raise NotImplementedError

    def container_status(self, container_id: str) -> Optional[ContainerRecord]:
        raise NotImplementedError

    def read_log(self, container_id: str, tail: int = 0) -> str:
        return ""

    def container_stats(self, container_id: str) -> Dict[str, float]:
        """Point-in-time usage {"cpu": cores, "memory": bytes} for the stats
        pipeline (ref: cadvisor ContainerStats → kubelet Summary API)."""
        return {"cpu": 0.0, "memory": 0.0}

    def exec_in_container(self, container_id: str, command) -> int:
        """Run a command in the container's context; returns exit code
        (exec probes + `ktpu exec` ride this)."""
        return -1

    def exec_capture(self, container_id: str, command) -> tuple:
        """ExecSync analog: (exit code, combined output) — the kubelet
        server's /exec endpoint (ref: CRI api.proto ExecSync)."""
        return self.exec_in_container(container_id, command), ""

    def exec_stream(self, container_id: str, command, tty: bool = False,
                    stdin: bool = False):
        """Streaming Exec (ref: CRI api.proto Exec): start the command in
        the container's context and return (popen, pty_master_fd or None).
        The caller owns the pumping.  None when unsupported."""
        return None

    def set_container_affinity(self, container_id: str, cpus) -> bool:
        """Re-pin a RUNNING container's process tree to `cpus` (the CPU
        manager's cpuset-update analog — the reference rewrites the cpuset
        cgroup of live containers when the shared pool changes).  Returns
        False when unsupported."""
        return False


class ImageService:
    """ref: api.proto ImageService (5 RPCs) — advisory here."""

    def __init__(self):
        self._images: set = set()

    def pull_image(self, image: str) -> str:
        self._images.add(image)
        return image

    def list_images(self) -> List[str]:
        return sorted(self._images)

    def image_present(self, image: str) -> bool:
        return image in self._images


# ------------------------------------------------------------ fake runtime


class FakeRuntime(RuntimeService):
    """Hollow runtime.  Containers run forever unless the config's command
    is ["sleep", "N"]-shaped or env KTPU_FAKE_EXIT_AFTER/_CODE is set, in
    which case they exit after N seconds with the given code."""

    def __init__(self):
        self._lock = locksan.make_rlock("FakeRuntime._lock")
        self._sandboxes: Dict[str, SandboxRecord] = {}
        self._containers: Dict[str, ContainerRecord] = {}
        self._exit_plans: Dict[str, tuple] = {}  # cid -> (deadline, code)
        self.images = ImageService()
        # hollow containers "run" as nobody: non-root, so runAsNonRoot
        # pods with image-declared users are exercisable in e2e tests
        self.default_uid = 65534
        # Synthetic usage for the stats pipeline: per-container-name override,
        # else the default. Tests drive HPA behavior through set_usage().
        self.default_usage: Dict[str, float] = {"cpu": 0.001, "memory": 1 << 20}
        self._usage_by_name: Dict[str, Dict[str, float]] = {}
        self._exec_results: Dict[str, int] = {}
        self.configs: Dict[str, ContainerConfig] = {}  # cid -> config, kept for assertions

    def set_usage(self, container_name: str, cpu: float, memory: float = 1 << 20):
        self._usage_by_name[container_name] = {"cpu": cpu, "memory": memory}

    def container_stats(self, container_id: str) -> Dict[str, float]:
        with self._lock:
            c = self._containers.get(container_id)
        if c is None or c.state != CONTAINER_RUNNING:
            return {"cpu": 0.0, "memory": 0.0}
        return dict(self._usage_by_name.get(c.name, self.default_usage))

    def set_exec_result(self, container_name: str, code: int):
        """Script exec-probe outcomes per container name (default 0)."""
        self._exec_results[container_name] = code

    def exec_in_container(self, container_id: str, command) -> int:
        with self._lock:
            c = self._containers.get(container_id)
        if c is None or c.state != CONTAINER_RUNNING:
            return -1
        return self._exec_results.get(c.name, 0)

    def version(self) -> str:
        return "fake://0.1"

    def run_pod_sandbox(self, pod_name, pod_namespace, pod_uid, labels=None) -> str:
        sid = f"sbx-{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._sandboxes[sid] = SandboxRecord(
                id=sid, pod_name=pod_name, pod_namespace=pod_namespace,
                pod_uid=pod_uid, labels=labels or {},
            )
        return sid

    def stop_pod_sandbox(self, sandbox_id: str):
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb:
                sb.state = SANDBOX_NOTREADY
            for c in self._containers.values():
                if c.sandbox_id == sandbox_id and c.state == CONTAINER_RUNNING:
                    self._finish(c, 137)

    def remove_pod_sandbox(self, sandbox_id: str):
        with self._lock:
            self._sandboxes.pop(sandbox_id, None)
            for cid in [c.id for c in self._containers.values() if c.sandbox_id == sandbox_id]:
                self._containers.pop(cid, None)

    def list_pod_sandboxes(self) -> List[SandboxRecord]:
        with self._lock:
            return list(self._sandboxes.values())

    def create_container(self, sandbox_id: str, config: ContainerConfig) -> str:
        cid = f"ctr-{uuid.uuid4().hex[:12]}"
        with self._lock:
            if sandbox_id not in self._sandboxes:
                raise KeyError(f"sandbox {sandbox_id} not found")
            self._containers[cid] = ContainerRecord(
                id=cid, sandbox_id=sandbox_id, name=config.name, image=config.image
            )
            self.configs[cid] = config  # tests assert on env/mount injection
            plan = self._plan_exit(config)
            if plan:
                self._exit_plans[cid] = plan
        return cid

    @staticmethod
    def _plan_exit(config: ContainerConfig):
        if "KTPU_FAKE_EXIT_AFTER" in config.env:
            return (
                float(config.env["KTPU_FAKE_EXIT_AFTER"]),
                int(config.env.get("KTPU_FAKE_EXIT_CODE", "0")),
            )
        cmd = (config.command or []) + (config.args or [])
        if len(cmd) == 2 and cmd[0] == "sleep":
            try:
                return (float(cmd[1]), 0)
            except ValueError:
                return None
        return None

    def start_container(self, container_id: str):
        with self._lock:
            c = self._containers[container_id]
            c.state = CONTAINER_RUNNING
            c.started_at = time.time()  # ktpulint: ignore[KTPU005] user-visible container status timestamp
            plan = self._exit_plans.get(container_id)
        if plan:
            delay, code = plan
            timer = threading.Timer(delay, self._timed_exit, args=(container_id, code))
            timer.daemon = True
            timer.start()

    def _timed_exit(self, container_id: str, code: int):
        with self._lock:
            c = self._containers.get(container_id)
            if c and c.state == CONTAINER_RUNNING:
                self._finish(c, code)

    def _finish(self, c: ContainerRecord, code: int):
        c.state = CONTAINER_EXITED
        c.exit_code = code
        c.finished_at = time.time()  # ktpulint: ignore[KTPU005] user-visible container status timestamp

    def stop_container(self, container_id: str, timeout: float = 10.0):
        with self._lock:
            c = self._containers.get(container_id)
            if c and c.state == CONTAINER_RUNNING:
                self._finish(c, 137)

    def remove_container(self, container_id: str):
        with self._lock:
            self._containers.pop(container_id, None)
            self._exit_plans.pop(container_id, None)
            self.configs.pop(container_id, None)

    def list_containers(self) -> List[ContainerRecord]:
        with self._lock:
            return list(self._containers.values())

    def container_status(self, container_id: str) -> Optional[ContainerRecord]:
        with self._lock:
            return self._containers.get(container_id)


# --------------------------------------------------------- process runtime


def _probe_mount_ns() -> bool:
    """True when this host can give containers private mount namespaces
    with bind mounts (root + unshare).  Probed once per runtime with a real
    bind, not just an unshare — unprivileged unshare can succeed while
    mount(2) fails."""
    if os.geteuid() != 0:
        return False
    try:
        res = subprocess.run(
            ["unshare", "--mount", "--propagation", "private", "sh", "-c",
             "mount --bind /tmp /tmp"],
            capture_output=True, timeout=10,
        )
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _pids_in_pgrp(pgid: int) -> List[int]:
    """All pids whose process group is `pgid` (field 5 of /proc/<p>/stat;
    the comm field is parenthesized and may contain spaces, so split after
    the closing paren)."""
    out = []
    try:
        entries = os.listdir("/proc")
    except OSError:
        return out
    for name in entries:
        if not name.isdigit():
            continue
        try:
            stat = open(f"/proc/{name}/stat").read()
            rest = stat.rsplit(")", 1)[1].split()
            if int(rest[2]) == pgid:  # rest: state, ppid, pgrp, ...
                out.append(int(name))
        except (OSError, IndexError, ValueError):
            continue
    return out


def _wrap_with_cgroups(cmd: List[str], procs_files: List[str]) -> List[str]:
    """Prefix `cmd` with a cgroup-join preamble: the sh writes itself into
    every cgroup.procs file, then execs the real command in place (same
    pid), so the whole future process tree is confined."""
    import shlex

    lines = []
    for pf in procs_files:
        # best-effort per file: a missing controller must not fail the start
        lines.append(f"echo 0 > {shlex.quote(pf)} 2>/dev/null || true")
    lines.append('exec "$@"')
    return ["sh", "-c", "\n".join(lines), "sh"] + list(cmd)


_TASKSET = shutil.which("taskset")
_SETPRIV = shutil.which("setpriv")


def _wrap_with_user(cmd: List[str], uid: Optional[int],
                    gid: Optional[int]) -> List[str]:
    """Prefix `cmd` with a setpriv exec dropping to uid/gid before the
    container command runs (ref: runc's process.user; pkg/securitycontext).
    Either may be None (gid defaults to uid; a gid-only request keeps the
    uid).  setpriv execs in place — same pid, privileges irrevocably
    dropped.  Raises when the host cannot honor the request: silently
    running a workload as the wrong identity is a security lie."""
    g = gid if gid is not None else uid
    need_uid = uid is not None and uid != os.geteuid()
    need_gid = g is not None and g != os.getegid()
    if not need_uid and not need_gid:
        return list(cmd)  # already the requested identity
    if os.geteuid() != 0:
        raise PermissionError(
            f"runAsUser/runAsGroup ({uid}/{g}) requires a root kubelet "
            f"(running as {os.geteuid()})")
    if not _SETPRIV:
        raise PermissionError("runAsUser/runAsGroup requested but setpriv "
                              "is not available on this host")
    args = [_SETPRIV]
    if uid is not None:
        args.append(f"--reuid={uid}")
    if g is not None:
        args += [f"--regid={g}", "--clear-groups"]
    return args + ["--"] + list(cmd)


def _wrap_with_cpuset(cmd: List[str], cpuset: List[int]) -> List[str]:
    """Prefix `cmd` with a taskset exec so the process (and every child it
    forks — JAX worker threads included) runs only on the assigned cpus.
    taskset execs in place: same pid, no extra process.  No-op when the
    binary is absent (pinning is best-effort beyond scheduling fit)."""
    if not _TASKSET:
        return list(cmd)
    spec = ",".join(str(c) for c in sorted(cpuset))
    return [_TASKSET, "-c", spec] + list(cmd)


def _wrap_with_mounts(cmd: List[str], mounts: List[dict]) -> List[str]:
    """Prefix `cmd` with an unshare+bind preamble realizing `mounts`
    ({host_path, container_path, read_only}) in a private mount namespace.
    Mount-point dirs are created on the shared fs (mkdir persists; the bind
    itself is namespace-private) — same as a host admin pre-creating
    mount points."""
    import shlex

    lines = ["set -e"]
    for m in mounts:
        src = m.get("host_path") or ""
        dst = m.get("container_path") or ""
        if not src or not dst or not os.path.exists(src):
            continue
        qsrc, qdst = shlex.quote(src), shlex.quote(dst)
        if os.path.isdir(src):
            lines.append(f"mkdir -p {qdst}")
        else:
            lines.append(f"mkdir -p $(dirname {qdst}) && touch {qdst}")
        lines.append(f"mount --bind {qsrc} {qdst}")
        if m.get("read_only"):
            lines.append(f"mount -o remount,ro,bind {qdst}")
    lines.append('exec "$@"')
    return [
        "unshare", "--mount", "--propagation", "private", "--",
        "sh", "-c", "\n".join(lines), "sh",
    ] + list(cmd)


class ProcessRuntime(RuntimeService):
    """Containers as host subprocesses (TPU-native local runtime).

    Sandbox = a log/working directory; container = a subprocess whose env is
    the merged pod env + device-plugin injection.  SIGTERM then SIGKILL on
    stop, honoring the grace timeout.
    """

    real_pids = True  # containers are real processes -> cgroups apply

    def __init__(self, root_dir: str = "/tmp/ktpu"):
        self.root = root_dir
        os.makedirs(os.path.join(self.root, "logs"), exist_ok=True)
        self._lock = locksan.make_rlock("ProcessRuntime._lock")
        self._sandboxes: Dict[str, SandboxRecord] = {}
        self._containers: Dict[str, ContainerRecord] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._configs: Dict[str, ContainerConfig] = {}
        self._stat_samples: Dict[str, tuple] = {}  # cid -> (cpu_ticks, mono_ts)
        self.images = ImageService()
        self._mount_ns = _probe_mount_ns()
        # identity a container with no runAsUser execs as (children are
        # forks of this process) — the kubelet's runAsNonRoot check reads it
        self.default_uid = os.geteuid()

    def version(self) -> str:
        return "process://0.1"

    def run_pod_sandbox(self, pod_name, pod_namespace, pod_uid, labels=None) -> str:
        sid = f"sbx-{uuid.uuid4().hex[:12]}"
        os.makedirs(os.path.join(self.root, "logs", sid), exist_ok=True)
        with self._lock:
            self._sandboxes[sid] = SandboxRecord(
                id=sid, pod_name=pod_name, pod_namespace=pod_namespace,
                pod_uid=pod_uid, labels=labels or {},
            )
        return sid

    def stop_pod_sandbox(self, sandbox_id: str):
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb:
                sb.state = SANDBOX_NOTREADY
            cids = [c.id for c in self._containers.values() if c.sandbox_id == sandbox_id]
        for cid in cids:
            self.stop_container(cid, timeout=2.0)

    def remove_pod_sandbox(self, sandbox_id: str):
        self.stop_pod_sandbox(sandbox_id)
        with self._lock:
            self._sandboxes.pop(sandbox_id, None)
            for cid in [c.id for c in self._containers.values() if c.sandbox_id == sandbox_id]:
                self._containers.pop(cid, None)
                self._procs.pop(cid, None)
                self._configs.pop(cid, None)

    def list_pod_sandboxes(self) -> List[SandboxRecord]:
        with self._lock:
            return list(self._sandboxes.values())

    def create_container(self, sandbox_id: str, config: ContainerConfig) -> str:
        cid = f"ctr-{uuid.uuid4().hex[:12]}"
        log_path = os.path.join(self.root, "logs", sandbox_id, f"{config.name}-{cid}.log")
        with self._lock:
            if sandbox_id not in self._sandboxes:
                raise KeyError(f"sandbox {sandbox_id} not found")
            self._containers[cid] = ContainerRecord(
                id=cid, sandbox_id=sandbox_id, name=config.name,
                image=config.image, log_path=log_path,
            )
            self._configs[cid] = config
        return cid

    def start_container(self, container_id: str):
        with self._lock:
            c = self._containers[container_id]
            config = self._configs[container_id]
        cmd = list(config.command or [])
        if not cmd:
            raise ValueError(f"container {config.name}: command required for process runtime")
        cmd += list(config.args or [])
        env = dict(os.environ)
        env.update(config.env)
        # Volume mounts: every mount is also exported as KTPU_VOLUME_<NAME>
        # (path-agnostic consumption), and — when the host permits mount
        # namespaces — bind-mounted at its container_path inside a private
        # mount ns, so /ckpt in one pod and /ckpt in another are different
        # directories exactly like real container runtimes.
        for m in config.mounts:
            name = (m.get("name") or "").replace("-", "_").replace(".", "_").upper()
            if name:
                env[f"KTPU_VOLUME_{name}"] = m.get("host_path", "")
        if config.run_as_user is not None or config.run_as_group is not None:
            # applied FIRST = innermost: the cgroup-join/mount/pinning
            # preambles run with the kubelet's privileges, then setpriv
            # drops to the container's uid/gid and execs the workload
            cmd = _wrap_with_user(cmd, config.run_as_user,
                                  config.run_as_group)
        if config.mounts and self._mount_ns:
            cmd = _wrap_with_mounts(cmd, config.mounts)
        if config.cgroup_procs_files:
            # the child joins its cgroups before exec (grandchildren inherit
            # at fork, so nothing can be spawned outside); done via an sh
            # preamble, NOT preexec_fn — Python-level I/O between fork and
            # exec can deadlock in a process with this many threads
            cmd = _wrap_with_cgroups(cmd, config.cgroup_procs_files)
        if config.cpuset:
            # CPU-manager pinning: affinity set before exec is inherited by
            # the whole future process tree (sched_setaffinity semantics)
            cmd = _wrap_with_cpuset(cmd, config.cpuset)
        logf = open(c.log_path, "ab")  # ktpulint: ignore[KTPU012] container stdout/stderr capture — workload output, not control-plane state; a torn log line loses no orchestration decision
        proc = subprocess.Popen(
            cmd,
            env=env,
            cwd=config.working_dir or None,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,  # isolate signals from the kubelet
        )
        with self._lock:
            self._procs[container_id] = proc
            c.state = CONTAINER_RUNNING
            c.started_at = time.time()  # ktpulint: ignore[KTPU005] user-visible container status timestamp

    def _reap(self, c: ContainerRecord):
        proc = self._procs.get(c.id)
        if proc is None:
            return
        code = proc.poll()
        if code is not None and c.state == CONTAINER_RUNNING:
            c.state = CONTAINER_EXITED
            c.exit_code = code
            c.finished_at = time.time()  # ktpulint: ignore[KTPU005] user-visible container status timestamp

    def set_container_affinity(self, container_id: str, cpus) -> bool:
        """Re-pin every thread of every process in the container's process
        group (containers start with start_new_session, so pgid == root
        pid).  This is how shared-pool containers get pushed OFF a core the
        CPU manager just assigned exclusively — taskset at exec time alone
        would leave them there."""
        with self._lock:
            proc = self._procs.get(container_id)
        if proc is None or proc.poll() is not None or not cpus:
            return False
        pgid = proc.pid
        ok = False
        for pid in _pids_in_pgrp(pgid):
            try:
                tids = os.listdir(f"/proc/{pid}/task")
            except OSError:
                continue
            for tid in tids:
                try:
                    os.sched_setaffinity(int(tid), cpus)
                    ok = True
                except (OSError, ValueError):
                    continue
        return ok

    def stop_container(self, container_id: str, timeout: float = 10.0):
        with self._lock:
            c = self._containers.get(container_id)
            proc = self._procs.get(container_id)
        if c is None or proc is None:
            return
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
        with self._lock:
            self._reap(c)
            if c.state == CONTAINER_RUNNING:  # defensive
                c.state = CONTAINER_EXITED
                c.exit_code = proc.returncode
                c.finished_at = time.time()  # ktpulint: ignore[KTPU005] user-visible container status timestamp

    def remove_container(self, container_id: str):
        self.stop_container(container_id, timeout=2.0)
        with self._lock:
            self._containers.pop(container_id, None)
            self._procs.pop(container_id, None)
            self._configs.pop(container_id, None)

    def kill_all(self) -> List[int]:
        """SIGKILL every tracked container process group and collect the
        exits; returns pids of any that SURVIVED (always [] in practice).
        The bench's teardown contract (VERDICT r4 Weak #1): a torn-down
        cluster must never leave a pod process running — a wedged payload
        held this box's only chip for hours."""
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        survivors = []
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                survivors.append(proc.pid)
        return survivors

    def list_containers(self) -> List[ContainerRecord]:
        with self._lock:
            for c in self._containers.values():
                self._reap(c)
            return list(self._containers.values())

    def container_status(self, container_id: str) -> Optional[ContainerRecord]:
        with self._lock:
            c = self._containers.get(container_id)
            if c:
                self._reap(c)
            return c

    def read_log(self, container_id: str, tail: int = 0) -> str:
        with self._lock:
            c = self._containers.get(container_id)
        if c is None or not os.path.exists(c.log_path):
            return ""
        with open(c.log_path, "r", errors="replace") as f:
            lines = f.readlines()
        if tail:
            lines = lines[-tail:]
        return "".join(lines)

    def exec_in_container(self, container_id: str, command) -> int:
        """Exec probes for process containers: run the command with the
        container's env (process analog of CRI ExecSync)."""
        return self.exec_capture(container_id, command)[0]

    def exec_capture(self, container_id: str, command) -> tuple:
        with self._lock:
            proc = self._procs.get(container_id)
            config = self._configs.get(container_id)
        if proc is None or proc.poll() is not None:
            return -1, "container not running"
        env = dict(os.environ)
        if config is not None:
            env.update(config.env)
        try:
            res = subprocess.run(
                list(command), env=env, capture_output=True, timeout=10,
                cwd=(config.working_dir or None) if config else None,
            )
            out = res.stdout.decode(errors="replace") + res.stderr.decode(errors="replace")
            return res.returncode, out
        except (OSError, subprocess.TimeoutExpired, ValueError) as e:
            return -1, str(e)

    def exec_stream(self, container_id: str, command, tty: bool = False,
                    stdin: bool = False):
        """Streaming exec with the container's env; tty=True allocates a
        pty so interactive shells behave (line editing, SIGINT)."""
        with self._lock:
            proc = self._procs.get(container_id)
            config = self._configs.get(container_id)
        if proc is None or proc.poll() is not None:
            return None
        env = dict(os.environ)
        if config is not None:
            env.update(config.env)
        cwd = (config.working_dir or None) if config else None
        if tty:
            import fcntl
            import pty
            import termios

            master, slave = pty.openpty()

            def acquire_ctty():
                # new session + make the pty the CONTROLLING terminal, so
                # ^C reaches the foreground process group (a single ioctl —
                # no Python allocation/IO between fork and exec)
                fcntl.ioctl(0, termios.TIOCSCTTY, 0)

            p = subprocess.Popen(
                list(command), env=env, cwd=cwd,
                stdin=slave, stdout=slave, stderr=slave,
                start_new_session=True, close_fds=True,
                preexec_fn=acquire_ctty,
            )
            os.close(slave)
            return p, master
        p = subprocess.Popen(
            list(command), env=env, cwd=cwd,
            stdin=subprocess.PIPE if stdin else subprocess.DEVNULL,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True,
        )
        return p, None

    def container_stats(self, container_id: str) -> Dict[str, float]:
        """CPU from /proc/<pid>/stat utime+stime deltas between calls, RSS
        from statm — per-process cadvisor-lite."""
        with self._lock:
            proc = self._procs.get(container_id)
        if proc is None or proc.poll() is not None:
            return {"cpu": 0.0, "memory": 0.0}
        try:
            with open(f"/proc/{proc.pid}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            ticks = int(parts[11]) + int(parts[12])  # utime, stime after comm
            with open(f"/proc/{proc.pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
        except (OSError, IndexError, ValueError):
            return {"cpu": 0.0, "memory": 0.0}
        now = time.monotonic()
        hz = os.sysconf("SC_CLK_TCK")
        mem = float(rss_pages * os.sysconf("SC_PAGE_SIZE"))
        with self._lock:
            last = self._stat_samples.get(container_id)
            self._stat_samples[container_id] = (ticks, now)
        if last is None or now <= last[1]:
            return {"cpu": 0.0, "memory": mem}
        cpu = (ticks - last[0]) / hz / (now - last[1])
        return {"cpu": max(0.0, cpu), "memory": mem}
