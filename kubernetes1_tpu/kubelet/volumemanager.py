"""Kubelet volume manager + pod environment construction.

Ref: pkg/kubelet/volumemanager/volume_manager.go:149 (desired/actual world
reconciler feeding mounts into container start) and
pkg/kubelet/kubelet_pods.go:591 (makeEnvironmentVariables: valueFrom /
envFrom / downward API / service-account token automount).

TPU-native shape: there is no cloud attach/detach step — every supported
source materializes to a host directory which the runtime bind-mounts into
the container's mount namespace (ProcessRuntime) or records (FakeRuntime):

- emptyDir                -> <root>/pods/<uid>/volumes/emptydir/<name>
                             (created on first mount, deleted with the pod —
                             pod-lifetime scratch, the checkpoint staging dir)
- hostPath                -> the host path itself (created if absent)
- configMap / secret      -> <root>/pods/<uid>/volumes/{configmap,secret}/<name>
                             one file per key, atomically refreshed when the
                             API object changes (the reference's AtomicWriter
                             ..data symlink dance collapsed to per-file
                             os.replace, which is atomic on one filesystem)
- persistentVolumeClaim   -> the bound PV's hostPath (local-storage model;
                             the PVC must be Bound — pods wait otherwise,
                             matching WaitForFirstConsumer behavior)
- downwardAPI             -> files rendered from pod fields
- service-account token   -> automounted at
                             /var/run/secrets/kubernetes.io/serviceaccount
                             {token, namespace} from the SA's token Secret
                             (ref: serviceaccount admission + token volume)

Secrets are written 0600 under a 0700 dir.  Refresh piggybacks on the
kubelet sync ticker: `refresh_pod` re-reads ConfigMap/Secret sources at most
once per `refresh_interval` per pod (the reference's cache-TTL analog).
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..api import types as t
from ..machinery import NotFound
from ..utils import faultline
from ..utils import locksan

SA_TOKEN_MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"
SA_TOKEN_VOLUME = "ktpu-sa-token"


class VolumeError(Exception):
    """Permanent volume failure (unknown source, missing required object)."""


class VolumeNotReady(Exception):
    """Transient: PVC unbound / object not yet visible; sync retries."""


@dataclass
class MountedVolume:
    name: str
    host_path: str
    read_only: bool = False  # source-level (secret/configmap dirs stay rw for refresh)
    kind: str = ""           # emptydir | hostpath | configmap | secret | pvc | downwardapi | satoken


class VolumeManager:
    """Materializes pod volumes into host directories and builds container
    environments.  One instance per kubelet; thread-safe (sync workers call
    concurrently for different pods)."""

    def __init__(self, clientset, root_dir: str, node_name: str = "",
                 refresh_interval: float = 10.0):
        self.cs = clientset
        self.root = root_dir
        self.node_name = node_name
        self.refresh_interval = refresh_interval
        self._lock = locksan.make_rlock("VolumeManager._lock")
        self._mounted: Dict[str, Dict[str, MountedVolume]] = {}  # uid -> name -> mv
        self._last_refresh: Dict[str, float] = {}

    # ------------------------------------------------------------- mounting

    def _pod_dir(self, uid: str) -> str:
        return os.path.join(self.root, "pods", uid, "volumes")

    def mount_pod(self, pod: t.Pod) -> Dict[str, MountedVolume]:
        """Ensure every volume in pod.spec.volumes (plus the automounted SA
        token) exists on disk; returns name -> MountedVolume.  Raises
        VolumeNotReady for unbound PVCs (caller treats as wait-and-retry)."""
        uid = pod.metadata.uid
        with self._lock:
            cached = self._mounted.get(uid)
        if cached is not None:
            # hot path: the sync ticker calls every second; content updates
            # are refresh_pod's job, so a mounted pod costs no API reads here
            return cached
        out: Dict[str, MountedVolume] = {}
        for vol in pod.spec.volumes:
            out[vol.name] = self._mount_volume(pod, vol)
        sa_mv = self._mount_sa_token(pod)
        if sa_mv is not None:
            out[SA_TOKEN_VOLUME] = sa_mv
        with self._lock:
            self._mounted[uid] = out
            # content is fresh as of now — refresh_pod must not re-fetch
            # everything again on the same sync pass
            self._last_refresh[uid] = time.monotonic()
        return out

    def _mount_volume(self, pod: t.Pod, vol: t.Volume) -> MountedVolume:
        uid = pod.metadata.uid
        ns = pod.metadata.namespace
        if vol.empty_dir is not None:
            path = os.path.join(self._pod_dir(uid), "emptydir", vol.name)
            os.makedirs(path, exist_ok=True)
            return MountedVolume(vol.name, path, kind="emptydir")
        if vol.host_path is not None:
            # an existing path is used as-is (file hostPaths are legal —
            # sockets, single config files); only a missing path becomes a dir
            if not os.path.exists(vol.host_path.path):
                os.makedirs(vol.host_path.path, exist_ok=True)
            return MountedVolume(vol.name, vol.host_path.path, kind="hostpath")
        if vol.config_map is not None:
            path = os.path.join(self._pod_dir(uid), "configmap", vol.name)
            try:
                cm = self.cs.configmaps.get(vol.config_map.name, ns)
            except NotFound:
                if vol.config_map.optional:
                    os.makedirs(path, exist_ok=True)
                    return MountedVolume(vol.name, path, True, kind="configmap")
                raise VolumeNotReady(f"configmap {ns}/{vol.config_map.name} not found")
            data = _select_items(cm.data, vol.config_map.items)
            _write_dir(path, data)
            return MountedVolume(vol.name, path, True, kind="configmap")
        if vol.secret is not None:
            path = os.path.join(self._pod_dir(uid), "secret", vol.name)
            try:
                sec = self.cs.secrets.get(vol.secret.secret_name, ns)
            except NotFound:
                if vol.secret.optional:
                    os.makedirs(path, exist_ok=True)
                    os.chmod(path, 0o700)
                    return MountedVolume(vol.name, path, True, kind="secret")
                raise VolumeNotReady(f"secret {ns}/{vol.secret.secret_name} not found")
            data = _select_items(sec.data, vol.secret.items)
            _write_dir(path, data, secret=True)
            return MountedVolume(vol.name, path, True, kind="secret")
        if vol.persistent_volume_claim is not None:
            claim = vol.persistent_volume_claim.claim_name
            try:
                pvc = self.cs.persistentvolumeclaims.get(claim, ns)
            except NotFound:
                raise VolumeNotReady(f"pvc {ns}/{claim} not found")
            if pvc.status.phase != "Bound" or not pvc.spec.volume_name:
                raise VolumeNotReady(f"pvc {ns}/{claim} is {pvc.status.phase or 'Pending'}, not Bound")
            try:
                pv = self.cs.persistentvolumes.get(pvc.spec.volume_name, "")
            except NotFound:
                raise VolumeNotReady(f"pv {pvc.spec.volume_name} not found")
            if pv.spec.host_path is None:
                raise VolumeError(
                    f"pv {pv.metadata.name}: only hostPath-backed PVs are "
                    f"mountable on this node (local-storage model)"
                )
            if not os.path.exists(pv.spec.host_path.path):
                os.makedirs(pv.spec.host_path.path, exist_ok=True)
            ro = bool(pvc.spec.access_modes) and set(pvc.spec.access_modes) == {"ReadOnlyMany"}
            return MountedVolume(vol.name, pv.spec.host_path.path, ro, kind="pvc")
        if vol.downward_api is not None:
            path = os.path.join(self._pod_dir(uid), "downwardapi", vol.name)
            data = {}
            for item in vol.downward_api.items:
                if item.field_ref is None or not item.path:
                    continue
                data[item.path] = resolve_field_ref(pod, item.field_ref.field_path,
                                                    self.node_name)
            _write_dir(path, data)
            return MountedVolume(vol.name, path, True, kind="downwardapi")
        raise VolumeError(f"volume {vol.name}: no supported source")

    def _mount_sa_token(self, pod: t.Pod) -> Optional[MountedVolume]:
        """Automount the ServiceAccount token (ref: serviceaccount admission
        plugin adds the token VolumeMount; here the volume manager does both
        halves node-side)."""
        from ..machinery import Forbidden

        sa_name = pod.spec.service_account_name or "default"
        ns = pod.metadata.namespace
        try:
            sa = self.cs.serviceaccounts.get(sa_name, ns)
        except NotFound:
            return None  # no SA machinery in this cluster (unit harnesses)
        except Forbidden:
            return None  # authz says this node may not read the SA: no automount
        if not sa.automount_service_account_token or not sa.secrets:
            return None
        try:
            sec = self.cs.secrets.get(sa.secrets[0].name, ns)
        except NotFound:
            return None
        token = sec.data.get("token", "")
        path = os.path.join(self._pod_dir(pod.metadata.uid), "satoken")
        _write_dir(path, {"token": token, "namespace": ns}, secret=True)
        return MountedVolume(SA_TOKEN_VOLUME, path, True, kind="satoken")

    # ------------------------------------------------------------- refresh

    def refresh_pod(self, pod: t.Pod):
        """Re-materialize configMap/secret/downwardAPI content if the
        refresh interval elapsed — mounted ConfigMap updates propagate to
        running pods (ref: the reference's configmap volume update)."""
        uid = pod.metadata.uid
        now = time.monotonic()
        with self._lock:
            if uid not in self._mounted:
                return
            if now - self._last_refresh.get(uid, 0.0) < self.refresh_interval:
                return
            self._last_refresh[uid] = now
        for vol in pod.spec.volumes:
            if vol.config_map is None and vol.secret is None and vol.downward_api is None:
                continue
            try:
                self._mount_volume(pod, vol)
            except (VolumeNotReady, VolumeError):
                pass  # keep serving the last-good content

    # ------------------------------------------------------------ teardown

    def teardown_pod(self, uid: str):
        """Delete pod-lifetime volume content (emptyDir, rendered
        configmap/secret/downward files).  hostPath and PV-backed data
        persists by design."""
        with self._lock:
            self._mounted.pop(uid, None)
            self._last_refresh.pop(uid, None)
        pod_root = os.path.join(self.root, "pods", uid)
        shutil.rmtree(pod_root, ignore_errors=True)

    def mounts_for_container(self, pod: t.Pod, container: t.Container) -> List[dict]:
        """Resolve container.volume_mounts against the pod's mounted volumes
        into the runtime mount dicts ({host_path, container_path, read_only}).
        The SA token mount is appended automatically."""
        with self._lock:
            mounted = dict(self._mounted.get(pod.metadata.uid, {}))
        out: List[dict] = []
        for vm in container.volume_mounts:
            mv = mounted.get(vm.name)
            if mv is None:
                raise VolumeError(
                    f"container {container.name}: volumeMount {vm.name!r} "
                    f"references no pod volume"
                )
            host = mv.host_path
            if vm.sub_path:
                sub = os.path.normpath(vm.sub_path)
                if sub.startswith("..") or os.path.isabs(sub):
                    raise VolumeError(f"volumeMount {vm.name}: invalid subPath {vm.sub_path!r}")
                host = os.path.join(host, sub)
                # a subPath may point at a rendered FILE (configmap key) —
                # only a missing subPath defaults to a directory
                if not os.path.exists(host):
                    os.makedirs(host, exist_ok=True)
            out.append({
                "name": vm.name,
                "host_path": host,
                "container_path": vm.mount_path,
                "read_only": vm.read_only or mv.read_only,
            })
        sa_mv = mounted.get(SA_TOKEN_VOLUME)
        if sa_mv is not None and not any(
            m["container_path"] == SA_TOKEN_MOUNT_PATH for m in out
        ):
            out.append({
                "name": SA_TOKEN_VOLUME,
                "host_path": sa_mv.host_path,
                "container_path": SA_TOKEN_MOUNT_PATH,
                "read_only": True,
            })
        return out

    # ---------------------------------------------------------- environment

    def make_environment(self, pod: t.Pod, container: t.Container) -> Dict[str, str]:
        """makeEnvironmentVariables (ref kubelet_pods.go:591): envFrom first
        (later sources win), then env, where explicit entries override
        envFrom and valueFrom resolves ConfigMap/Secret keys and downward
        fields."""
        ns = pod.metadata.namespace
        env: Dict[str, str] = {}
        for src in container.env_from:
            if src.config_map_ref is not None:
                try:
                    data = self.cs.configmaps.get(src.config_map_ref.name, ns).data
                except NotFound:
                    if src.config_map_ref.optional:
                        continue
                    raise VolumeNotReady(f"envFrom configmap {ns}/{src.config_map_ref.name} not found")
            elif src.secret_ref is not None:
                try:
                    data = self.cs.secrets.get(src.secret_ref.name, ns).data
                except NotFound:
                    if src.secret_ref.optional:
                        continue
                    raise VolumeNotReady(f"envFrom secret {ns}/{src.secret_ref.name} not found")
            else:
                continue
            for k, v in data.items():
                env[f"{src.prefix}{k}"] = str(v)
        for e in container.env:
            if e.value_from is None:
                env[e.name] = e.value
                continue
            vf = e.value_from
            if vf.config_map_key_ref is not None:
                ref = vf.config_map_key_ref
                try:
                    data = self.cs.configmaps.get(ref.name, ns).data
                except NotFound:
                    if ref.optional:
                        continue
                    raise VolumeNotReady(f"configmap {ns}/{ref.name} not found")
                if ref.key not in data:
                    if ref.optional:
                        continue
                    raise VolumeError(f"key {ref.key!r} not in configmap {ref.name}")
                env[e.name] = str(data[ref.key])
            elif vf.secret_key_ref is not None:
                ref = vf.secret_key_ref
                try:
                    data = self.cs.secrets.get(ref.name, ns).data
                except NotFound:
                    if ref.optional:
                        continue
                    raise VolumeNotReady(f"secret {ns}/{ref.name} not found")
                if ref.key not in data:
                    if ref.optional:
                        continue
                    raise VolumeError(f"key {ref.key!r} not in secret {ref.name}")
                env[e.name] = str(data[ref.key])
            elif vf.field_ref is not None:
                env[e.name] = resolve_field_ref(pod, vf.field_ref.field_path,
                                                self.node_name)
        return env


def resolve_field_ref(pod: t.Pod, field_path: str, node_name: str = "") -> str:
    """Downward-API field resolution (ref: pkg/fieldpath/fieldpath.go)."""
    simple = {
        "metadata.name": pod.metadata.name,
        "metadata.namespace": pod.metadata.namespace,
        "metadata.uid": pod.metadata.uid,
        "spec.nodeName": pod.spec.node_name or node_name,
        "spec.serviceAccountName": pod.spec.service_account_name,
        "status.podIP": pod.status.pod_ip,
        "status.hostIP": pod.status.host_ip or node_name,
    }
    if field_path in simple:
        return simple[field_path] or ""
    for prefix, mapping in (
        ("metadata.labels", pod.metadata.labels),
        ("metadata.annotations", pod.metadata.annotations),
    ):
        if field_path.startswith(prefix + "["):
            key = field_path[len(prefix) + 1:].rstrip("]").strip("'\"")
            return str(mapping.get(key, ""))
    return ""


def _select_items(data: Dict[str, str], items: List[t.KeyToPath]) -> Dict[str, str]:
    if not items:
        return {k: str(v) for k, v in data.items()}
    out = {}
    for kp in items:
        if kp.key in data:
            out[kp.path or kp.key] = str(data[kp.key])
    return out


def _write_dir(path: str, data: Dict[str, str], secret: bool = False):
    """Render {filename: content} into `path`, atomically per file, pruning
    files (including nested `items`-projected paths) whose keys are gone."""
    os.makedirs(path, exist_ok=True)
    if secret:
        os.chmod(path, 0o700)
    keep = set()
    for fname, content in data.items():
        safe = os.path.normpath(fname)
        if safe.startswith("..") or os.path.isabs(safe):
            continue  # a key must not escape the volume dir
        keep.add(safe)
        target = os.path.join(path, safe)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        tmp = target + ".ktpu-tmp"
        faultline.check("kubelet.statefile")  # volume materialization write
        with open(tmp, "w") as f:
            f.write(str(content))
        if secret:
            os.chmod(tmp, 0o600)
        os.replace(tmp, target)
    for dirpath, _dirs, files in os.walk(path):
        for fname in files:
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, path)
            if rel not in keep and not rel.endswith(".ktpu-tmp"):
                os.unlink(full)
