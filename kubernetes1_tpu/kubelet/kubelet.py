"""Kubelet: the per-node agent realizing bound pods into running containers.

Ref: pkg/kubelet/kubelet.go — Run (:1361) starts the status/heartbeat loops,
PLEG and syncLoop (:1772/:1839); per-pod workers (pod_workers.go); syncPod
(:1441) = admission -> sandbox -> containers -> status.  The TPU path
threads through the device manager exactly where the fork put it:
AdmitPod at pod admission (container_manager_linux.go:619-621) and
InitContainer before each container start (kubelet_pods.go:468 ->
GenerateRunContainerOptions).

Structure here:
- pod source = apiserver informer filtered to spec.nodeName==<me> plus an
  optional static-manifest directory (ref: config/apiserver.go, file source);
- a work queue of pod keys drives N sync workers; PLEG (1s relist) and a
  periodic ticker both enqueue;
- status truth flows one way: runtime state -> computed PodStatus -> status
  subresource PUT when changed (status_manager.go:131,399).
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..client import Clientset, EventRecorder, SharedInformer
from ..client import retry as _retry
from ..machinery import ApiError, Conflict, NotFound, now_iso
from ..machinery.scheme import global_scheme
from ..utils import faultline, locksan
from ..utils.spans import SpanCollector
from ..utils.workqueue import WorkQueue
from ..deviceplugin.api import DEFAULT_PLUGIN_DIR
from .devicemanager import DeviceManager
from .runtime import (
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    ContainerConfig,
    RuntimeService,
)
from .containermanager import ContainerManager
from .cpumanager import POLICY_NONE, CPUExhaustedError, CPUManager
from .volumemanager import VolumeError, VolumeManager, VolumeNotReady



class Kubelet:
    def __init__(
        self,
        clientset: Clientset,
        node_name: str,
        runtime: RuntimeService,
        plugin_dir: str = DEFAULT_PLUGIN_DIR,
        static_pod_dir: Optional[str] = None,
        node_labels: Optional[Dict[str, str]] = None,
        capacity: Optional[Dict[str, str]] = None,
        heartbeat_interval: float = 5.0,
        sync_interval: float = 1.0,
        pleg_interval: float = 1.0,
        restart_backoff_base: float = 1.0,
        sync_workers: int = 4,
        eviction_interval: float = 10.0,
        eviction_thresholds: Optional[Dict[str, float]] = None,
        eviction_signals_fn=None,
        podscrape_interval: float = 1.0,
        server_port: Optional[int] = 0,  # 0 = ephemeral; None = no server
        server_token: str = "",
        server_tls_cert_file: str = "",  # CSR-issued serving cert (:10250 TLS)
        server_tls_key_file: str = "",
        volume_root: Optional[str] = None,
        enforce_cgroups: Optional[bool] = None,  # None = auto (real runtimes only)
        system_reserved: Optional[Dict[str, str]] = None,
        cpu_manager_policy: Optional[str] = None,  # None = "none"
        cpu_manager_state_dir: str = "",
        cluster_dns: bool = True,  # node-local resolver (real runtimes only)
    ):
        self.cs = clientset
        self.node_name = node_name
        self.runtime = runtime
        self.device_manager = DeviceManager(plugin_dir)
        self.device_manager.on_capacity_change = self._heartbeat_now
        self.device_manager.on_device_unhealthy = self._on_device_unhealthy
        self.static_pod_dir = static_pod_dir
        self.node_labels = node_labels or {}
        self.capacity = capacity or self._default_capacity()
        self.heartbeat_interval = heartbeat_interval
        self.sync_interval = sync_interval
        self.pleg_interval = pleg_interval
        self.restart_backoff_base = restart_backoff_base
        self.sync_workers = sync_workers
        self.recorder = EventRecorder(clientset, f"kubelet/{node_name}")
        # Volume roots must be node-unique: many hollow kubelets share one
        # process in scale tests, and two nodes' emptyDirs must not collide.
        runtime_root = getattr(runtime, "root", None)
        self.volume_manager = VolumeManager(
            clientset,
            volume_root or (
                os.path.join(runtime_root, "volumes") if runtime_root
                else os.path.join("/tmp/ktpu-volumes", node_name)
            ),
            node_name=node_name,
        )
        # cgroup enforcement only makes sense for runtimes with real
        # processes: hollow/Fake runtimes (30k-pod scale tests) must not
        # create 30k cgroup dirs.  ProcessRuntime advertises via real_pids.
        # For a RemoteRuntime this is a live socket call against a runtime
        # that may still be starting (kubelet + runtime boot concurrently);
        # the upstream kubelet blocks on the CRI socket before proceeding
        # (cmd/kubelet/app/server.go), so wait briefly rather than freezing
        # a False answer for the life of the process.
        real_pids = self._probe_real_pids(runtime)
        if enforce_cgroups is None:
            enforce_cgroups = real_pids
        # node-local cluster DNS (ref --cluster-dns + kube-dns addon; see
        # dns/server.py): real-process runtimes only — hollow nodes must
        # not each open informers and a resolver socket.  Binding the
        # loopback alias needs root/port-53 rights; fall back to no DNS
        # (env-injection still works) when the host refuses.
        self.cluster_dns = None
        if real_pids and cluster_dns:
            try:
                from ..dns import ClusterDNS

                self.cluster_dns = ClusterDNS(clientset)
            except OSError:
                pass
        self.container_manager = ContainerManager(
            node_name,
            system_reserved=system_reserved,
            enforce=enforce_cgroups,
        )
        # CPU manager (ref cm/cpumanager): static pinning only for runtimes
        # with real processes; state checkpoint lives beside the runtime root
        state_dir = cpu_manager_state_dir or runtime_root or ""
        self.cpu_manager = CPUManager(
            policy=(cpu_manager_policy or POLICY_NONE)
            if real_pids else POLICY_NONE,
            state_path=os.path.join(state_dir, "cpu_manager_state.json")
            if state_dir else "",
        )

        self.pods = SharedInformer(
            clientset.pods, field_selector=f"spec.nodeName={node_name}"
        )
        self._queue = WorkQueue()
        self._sandboxes: Dict[str, str] = {}  # pod uid -> sandbox id
        self._containers: Dict[Tuple[str, str], str] = {}  # (uid, cname) -> cid
        self._restart_at: Dict[Tuple[str, str], float] = {}
        self._restarts: Dict[Tuple[str, str], int] = {}
        self._admitted: Dict[str, Tuple[str, str]] = {}
        self._admit_first_seen: Dict[str, float] = {}
        self._last_status: Dict[str, dict] = {}  # uid -> last PUT status dict
        self._pleg_state: Dict[str, str] = {}
        self._mount_warned: set = set()  # uids with a FailedMount event emitted
        self._oom_baseline: Dict[str, int] = {}   # uid -> consumed oom_kill count
        self._oom_marked: set = set()             # (uid, container_id) OOMKilled
        self._heartbeat_event = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = locksan.make_rlock("Kubelet._lock")
        self._metrics_rv: Dict[Tuple[str, str], str] = {}  # (kind, key) -> rv
        # per-pod spans under the creating request's trace id (utils/spans),
        # served at the kubelet server's /debug/traces
        self.spans = SpanCollector(f"kubelet/{node_name}")
        # pod /metrics scrape agent (custom-metrics pipeline): reconciled
        # from the stats loop, scraping happens on per-pod threads — a
        # dead pod endpoint can never stall the kubelet's own loops
        from .podscrape import PodScraper

        self.pod_scraper = PodScraper(
            clientset, node_name, interval=podscrape_interval)

        self.server = None
        self.server_token = server_token
        if server_port is not None:
            import secrets

            from .server import KubeletServer

            # exec must never be an open door: without an explicit token we
            # mint one and publish it ONLY via the Node annotation, so the
            # ability to exec is gated on apiserver node-read authorization —
            # the shape of the reference's delegated nodes/proxy authz
            if not self.server_token:
                self.server_token = secrets.token_hex(16)
            self.server = KubeletServer(self, port=server_port,
                                        token=self.server_token,
                                        tls_cert_file=server_tls_cert_file,
                                        tls_key_file=server_tls_key_file)

        from .eviction import EvictionManager, default_signals
        from .prober import ProberManager

        self.prober = ProberManager(
            exec_in_container=self._exec_in_container,
            container_running=self._container_running,
        )
        self.eviction_interval = eviction_interval
        self.eviction = EvictionManager(
            thresholds=eviction_thresholds,
            signals_fn=eviction_signals_fn or default_signals,
            evict_fn=self._evict_pod,
            list_pods=self._my_pods,
        )

    @staticmethod
    def _probe_real_pids(runtime, wait: float = 10.0) -> bool:
        """Resolve runtime.real_pids, waiting out a not-yet-listening CRI
        endpoint first.  RemoteRuntime.real_pids swallows dial failures and
        answers False, so probe reachability via version() (which raises);
        once the endpoint answers anything, real_pids is authoritative —
        RemoteRuntime deliberately doesn't cache failed capability reads."""
        deadline = time.monotonic() + wait
        probe = getattr(runtime, "version", None)
        backoff = _retry.Backoff(base=0.1, factor=2.0, cap=0.4)
        while callable(probe):
            try:
                probe()
                break
            except (ConnectionError, OSError):
                if time.monotonic() >= deadline:
                    break
                backoff.sleep()
            except RuntimeError:
                # the endpoint answered (an error response still needed a
                # full round-trip; in-process stubs may not implement
                # version at all) — reachability is established
                break
        return bool(getattr(runtime, "real_pids", False))

    # ---------------------------------------------------------------- start

    @staticmethod
    def _default_capacity() -> Dict[str, str]:
        cpus = os.cpu_count() or 4
        mem_kb = 8 * 1024 * 1024
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        mem_kb = int(line.split()[1])
                        break
        except OSError:
            pass
        return {"cpu": str(cpus), "memory": f"{mem_kb}Ki", "pods": "110"}

    def start(self):
        from ..utils.features import gates
        from ..utils.gctune import tune_for_server

        tune_for_server()

        if gates.enabled("DevicePlugins"):
            self.device_manager.start()
        if self.server is not None:
            self.server.start()
        if self.cluster_dns is not None:
            self.cluster_dns.start()
        self._reconcile_runtime()
        self._register_node()
        self.pods.add_handler(
            on_add=lambda p: self._enqueue(p),
            on_update=lambda _o, p: self._enqueue(p),
            on_delete=self._enqueue,
        )
        self.pods.start()
        self.pods.wait_for_sync()
        # CPU-manager state vs world: drop checkpointed exclusive
        # assignments for pods deleted while the kubelet was down (the
        # informer never delivers a delete for an already-gone pod), and
        # re-pin running shared containers whenever the pool changes
        self.cpu_manager.on_pool_change = self._reapply_shared_cpusets
        if self.cpu_manager.enabled:
            live = {p.metadata.uid for p in self.pods.list()}
            self.cpu_manager.reconcile(live)
        if self.static_pod_dir:
            self._load_static_pods()
        for i in range(self.sync_workers):
            th = threading.Thread(target=self._sync_worker, daemon=True, name=f"sync-{i}")
            th.start()
            self._threads.append(th)
        for fn, period_attr, name in (
            (self._heartbeat, "heartbeat_interval", "heartbeat"),
            (self._pleg_relist, "pleg_interval", "pleg"),
            (self._tick_all, "sync_interval", "sync-ticker"),
            (self._publish_metrics, "heartbeat_interval", "stats"),
            (self._eviction_pass, "eviction_interval", "eviction"),
            # ref cpu_manager.go reconcileState: event-driven repinning
            # races container exec (a shared container created before a
            # grant but execed after it misses the on_pool_change), so a
            # periodic pass restores the invariant within one sync period
            (self._cpuset_reconcile, "sync_interval", "cpuset-reconcile"),
        ):
            th = threading.Thread(
                target=self._loop, args=(fn, period_attr), daemon=True, name=name
            )
            th.start()
            self._threads.append(th)
        return self

    def stop(self):
        self._stop.set()
        self._queue.shut_down()
        self.pods.stop()
        self.pod_scraper.stop()
        self.device_manager.stop()
        self.prober.stop()
        self.container_manager.cleanup()
        if self.server is not None:
            self.server.stop()
        if self.cluster_dns is not None:
            self.cluster_dns.stop()

    def _loop(self, fn, period_attr: str):
        # the period is re-read each cycle so dynamic kubelet config can
        # retune a live kubelet without restarting its loops
        while not self._stop.is_set():
            try:
                fn()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            period = getattr(self, period_attr)
            if fn is self._heartbeat:
                # wake immediately on capacity change
                self._heartbeat_event.wait(period)
                self._heartbeat_event.clear()
            else:
                self._stop.wait(period)

    def _heartbeat_now(self):
        self._heartbeat_event.set()

    def _cpuset_reconcile(self):
        if self.cpu_manager.enabled and self.cpu_manager.assigned_cpus():
            self._reapply_shared_cpusets()

    def _reapply_shared_cpusets(self):
        """Shared (non-exclusive) containers were taskset-pinned to the pool
        as of their exec; when the CPU manager's pool changes (exclusive
        grant or release) push the RUNNING ones onto the current pool so
        none keeps running on a newly-exclusive core (the reference updates
        live cpuset cgroups the same way)."""
        pool = self.cpu_manager.shared_pool()
        if pool is None:
            return
        exclusive = set(self.cpu_manager.assigned_cpus())
        with self._lock:
            containers = dict(self._containers)
        for (uid, cname), cid in containers.items():
            if f"{uid}/{cname}" in exclusive:
                continue
            try:
                self.runtime.set_container_affinity(cid, pool)
            except (OSError, RuntimeError, KeyError):  # best-effort, container may be gone
                continue

    def _reconcile_runtime(self):
        """Adopt pre-existing runtime state after a kubelet restart: rebuild
        the sandbox/container maps from the runtime's own records so running
        workloads are NOT duplicated (the reference kubelet rebuilds from the
        CRI the same way; restart-safety e2e relies on this)."""
        sandbox_by_uid: Dict[str, str] = {}
        for sb in self.runtime.list_pod_sandboxes():
            uid = sb.labels.get("pod-uid") or sb.pod_uid
            if uid:
                sandbox_by_uid[uid] = sb.id
        sandbox_to_uid = {sid: uid for uid, sid in sandbox_by_uid.items()}
        containers: Dict[Tuple[str, str], str] = {}
        for c in self.runtime.list_containers():
            uid = sandbox_to_uid.get(c.sandbox_id)
            if uid is None:
                continue
            ckey = (uid, c.name)
            prev = containers.get(ckey)
            if prev is None:
                containers[ckey] = c.id
            else:
                # prefer the running record over exited leftovers
                prev_rec = self.runtime.container_status(prev)
                if prev_rec is None or prev_rec.state != CONTAINER_RUNNING:
                    containers[ckey] = c.id
        with self._lock:
            self._sandboxes.update(sandbox_by_uid)
            self._containers.update(containers)

    # ----------------------------------------------------------- node status

    KUBELET_SERVER_ANNOTATION = "kubelet.ktpu.io/server"
    # The per-kubelet bearer token lives in a kube-system Secret only the
    # apiserver (and this node, via the node authorizer) can read — NOT in a
    # Node annotation, which every kubelet can read (ADVICE r2: that enabled
    # cluster-wide lateral movement through any one compromised node).
    TOKEN_SECRET_NS = "kube-system"

    @staticmethod
    def token_secret_name(node_name: str) -> str:
        return f"kubelet-token-{node_name}"

    def _node_object(self) -> t.Node:
        node = t.Node()
        node.metadata.name = self.node_name
        node.metadata.labels = {
            "kubernetes.io/hostname": self.node_name,
            **self.node_labels,
        }
        if self.server is not None:
            # clients resolve the kubelet endpoint from this (the :10250
            # daemonEndpoints analog); the credential travels separately
            node.metadata.annotations[self.KUBELET_SERVER_ANNOTATION] = self.server.url
        self._fill_status(node)
        return node

    def _publish_token_secret(self):
        if self.server is None:
            return
        sec = t.Secret(type="ktpu.io/kubelet-token",
                       data={"token": self.server_token})
        sec.metadata.name = self.token_secret_name(self.node_name)
        sec.metadata.namespace = self.TOKEN_SECRET_NS
        try:
            self.cs.secrets.create(sec, self.TOKEN_SECRET_NS)
        except ApiError:
            try:
                self.cs.secrets.patch(
                    sec.metadata.name, {"data": {"token": self.server_token}},
                    namespace=self.TOKEN_SECRET_NS,
                )
            except ApiError:
                traceback.print_exc()

    def _fill_status(self, node: t.Node):
        node.status.capacity = dict(self.capacity)
        node.status.allocatable = self.container_manager.node_allocatable(
            self.capacity)
        now = now_iso()
        node.status.conditions = [
            t.NodeCondition(
                type=t.NODE_READY,
                status="True",
                reason="KubeletReady",
                last_heartbeat_time=now,
            )
        ] + self.eviction.node_conditions()
        node.status.addresses = [t.NodeAddress(type="Hostname", address=self.node_name)]
        node.status.node_info = t.NodeSystemInfo(
            kubelet_version="ktpu-0.1",
            container_runtime_version=self.runtime.version(),
            architecture=os.uname().machine,
            os_image="linux",
        )
        node.status.extended_resources = self.device_manager.get_capacity()
        # image inventory feeds the scheduler's ImageLocality priority
        # (ref kubelet_node_status.go setNodeStatusImages)
        image_svc = getattr(self.runtime, "images", None)
        if image_svc is not None:
            try:
                node.status.images = image_svc.list_images()
            except ConnectionError:
                pass  # remote runtime hiccup: keep the previous inventory

    def _register_node(self):
        node = self._node_object()
        try:
            # Registration must survive a transport-level reset: the REST
            # layer refuses to re-send a mutation whose response was lost
            # (may-have-been-applied), but node create is safe to retry —
            # an applied first attempt surfaces as ApiError(exists) on the
            # next one, which the handler below already expects.  Without
            # this, a reset during boot kills the whole kubelet.
            _retry.call_with_retries(
                lambda: self.cs.nodes.create(node), reason="node_register")
        except ApiError:
            # exists: heartbeat will refresh status, but the server endpoint
            # lives in metadata (a restart may listen on a new port)
            if self.server is not None:
                try:
                    self.cs.nodes.patch(
                        self.node_name,
                        {"metadata": {"annotations": {
                            self.KUBELET_SERVER_ANNOTATION: self.server.url,
                            # explicit null: scrub the world-readable token
                            # annotation older kubelets published (merge
                            # patch deletes null keys) — without this an
                            # upgraded node keeps leaking a valid token
                            "kubelet.ktpu.io/exec-token": None,
                        }}},
                        namespace="",
                    )
                except ApiError:
                    pass
        self._publish_token_secret()

    TOKEN_RECHECK_BEATS = 12  # verify the token secret every ~minute

    def _heartbeat(self):
        """10s-class syncNodeStatus (ref: kubelet_node_status.go:545-621)."""
        try:
            node = self.cs.nodes.get(self.node_name, "")
        except NotFound:
            self._register_node()
            return
        self._fill_status(node)
        try:
            self.cs.nodes.update_status(node)
        except Conflict:
            pass  # next beat wins
        # the token secret must outlive registration hiccups and admin
        # deletions — without it every apiserver-proxied logs/exec 401s
        self._beats = getattr(self, "_beats", 0) + 1
        if self.server is not None and self._beats % self.TOKEN_RECHECK_BEATS == 0:
            try:
                self.cs.secrets.get(
                    self.token_secret_name(self.node_name), self.TOKEN_SECRET_NS)
            except NotFound:
                self._publish_token_secret()
            except ApiError:
                pass
        if self._beats % self.TOKEN_RECHECK_BEATS == 0:
            self._sync_dynamic_config()

    # ------------------------------------------------ dynamic kubelet config

    # fields a live kubelet re-tunes (ref kubeletconfig/controller.go)
    _DYNAMIC_FIELDS = (
        ("sync_interval_seconds", "sync_interval"),
        ("heartbeat_interval_seconds", "heartbeat_interval"),
        ("pleg_interval_seconds", "pleg_interval"),
    )

    def _sync_dynamic_config(self):
        """DynamicKubeletConfig (feature-gated): live-reload tuning from a
        kube-system ConfigMap — per-node kubelet-config-<node> wins over the
        cluster-wide kubelet-config.  Invalid payloads keep the last-known-
        good settings (the reference's rollback semantics collapsed to
        'never apply what doesn't validate')."""
        from ..utils.features import gates

        if not gates.enabled("DynamicKubeletConfig"):
            return
        cm = None
        for name in (f"kubelet-config-{self.node_name}", "kubelet-config"):
            try:
                cm = self.cs.configmaps.get(name, self.TOKEN_SECRET_NS)
                break
            except NotFound:
                continue
            except ApiError:
                return
        if cm is None:
            return
        rv = cm.metadata.resource_version
        if rv == getattr(self, "_config_rv", None):
            return
        self._config_rv = rv  # seen (good or bad); a new write retries
        try:
            from ..machinery.scheme import from_dict

            data = json.loads(cm.data.get("kubelet", "{}"))
            cfg = from_dict(t.KubeletConfiguration, data)
            self._validate_kubelet_config(cfg)
        except (ValueError, TypeError, KeyError) as e:
            self.recorder.event(
                self._node_object(), "Warning", "InvalidKubeletConfig",
                f"configmap {cm.metadata.name}: {e}; keeping last-known-good",
            )
            return
        for src, dst in self._DYNAMIC_FIELDS:
            val = getattr(cfg, src)
            if val is not None:
                setattr(self, dst, float(val))
        if cfg.max_pods is not None:
            self.capacity["pods"] = str(cfg.max_pods)
        if cfg.eviction_thresholds:
            self.eviction.thresholds = dict(cfg.eviction_thresholds)
        if cfg.volume_refresh_interval_seconds is not None:
            self.volume_manager.refresh_interval = float(
                cfg.volume_refresh_interval_seconds)
        self.recorder.event(
            self._node_object(), "Normal", "KubeletConfigApplied",
            f"applied {cm.metadata.name} rv={rv}",
        )

    @staticmethod
    def _validate_kubelet_config(cfg: "t.KubeletConfiguration"):
        for fname in ("sync_interval_seconds", "heartbeat_interval_seconds",
                      "pleg_interval_seconds", "volume_refresh_interval_seconds"):
            val = getattr(cfg, fname)
            if val is not None and (not isinstance(val, (int, float)) or val <= 0):
                raise ValueError(f"{fname} must be a positive number, got {val!r}")
        if cfg.max_pods is not None and (
                not isinstance(cfg.max_pods, int) or cfg.max_pods < 1):
            raise ValueError(f"maxPods must be a positive integer, got {cfg.max_pods!r}")
        for sig, frac in cfg.eviction_thresholds.items():
            if not isinstance(frac, (int, float)) or not 0 <= frac <= 1:
                raise ValueError(
                    f"eviction threshold {sig}={frac!r} must be a 0..1 fraction")

    # -------------------------------------------------- probes and eviction

    def _container_running(self, pod_uid: str, container_name: str) -> bool:
        with self._lock:
            cid = self._containers.get((pod_uid, container_name))
        if cid is None:
            return False
        record = self.runtime.container_status(cid)
        return record is not None and record.state == CONTAINER_RUNNING

    def _exec_in_container(self, pod_uid: str, container_name: str, command) -> int:
        with self._lock:
            cid = self._containers.get((pod_uid, container_name))
        if cid is None:
            return -1
        exec_fn = getattr(self.runtime, "exec_in_container", None)
        if exec_fn is None:
            return -1
        return exec_fn(cid, command)

    def _my_pods(self) -> List[t.Pod]:
        return [p for p in self.pods.list() if p.spec.node_name == self.node_name]

    def _evict_pod(self, pod: t.Pod, reason: str):
        """Pressure eviction = fail the pod; its controller reschedules it
        elsewhere (ref: eviction_manager.go evictPod)."""
        self.recorder.event(pod, "Warning", "Evicted", reason)
        self._set_failed(pod, "Evicted", reason)
        self._heartbeat_now()  # surface the pressure condition promptly

    def _on_device_unhealthy(self, resource: str, dead_ids):
        """A plugin reported chips dead (ListAndWatch unhealthy): fail every
        pod holding one of them.  Admit-time checks only protect FUTURE
        pods; an already-running pod on a bricked chip makes no progress
        until its controller (the gang failure policy) replaces it — every
        second here is lost goodput.  Runs on the endpoint's watch thread;
        _set_failed is a plain status PUT, safe off-loop."""
        dead = set(dead_ids)
        for pod in self.pods.list():
            if (pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED)
                    or pod.metadata.deletion_timestamp):
                continue
            held = {
                dev_id
                for per in pod.spec.extended_resources
                if per.resource == resource
                for dev_id in per.assigned
            }
            hit = held & dead
            if not hit:
                continue
            msg = (f"assigned device(s) {sorted(hit)} went unhealthy; "
                   f"failing pod so its controller can re-place it")
            self.recorder.event(pod, "Warning", "DeviceUnhealthy", msg)
            self._set_failed(pod, "DeviceUnhealthy", msg)

    def _eviction_pass(self):
        self.eviction.synchronize()

    # -------------------------------------------------------- stats pipeline

    def _container_usage(self, pod_uid: str, cname: str, cid: str) -> Dict[str, float]:
        """Cgroup ground truth when enforced (counts the whole process tree,
        not just the direct child), else the runtime's own sampling."""
        cg = self.container_manager.container_stats(pod_uid, cname)
        if cg is not None:
            return cg
        return self.runtime.container_stats(cid)

    @staticmethod
    def _fmt_usage(stats: Dict[str, float]) -> Dict[str, str]:
        return {
            "cpu": f"{int(round(stats.get('cpu', 0.0) * 1000))}m",
            "memory": str(int(stats.get("memory", 0.0))),
        }

    def _upsert_metrics(self, client, obj, namespace: str = ""):
        # Steady state is update (the object exists after the first cycle);
        # create only on the first publish or after a GC.
        cached = self._metrics_rv.get((type(obj).KIND, obj.key()))
        try:
            if cached is not None:
                obj.metadata.resource_version = cached
                updated = client.update(obj)
            else:
                updated = client.create(obj, namespace)
        except NotFound:
            try:
                updated = client.create(obj, namespace)
            except ApiError:
                return
        except ApiError:  # Conflict/AlreadyExists: refresh rv, next cycle wins
            try:
                cur = client.get(obj.metadata.name, obj.metadata.namespace)
                self._metrics_rv[(type(obj).KIND, obj.key())] = cur.metadata.resource_version
            except ApiError:
                self._metrics_rv.pop((type(obj).KIND, obj.key()), None)
            return
        self._metrics_rv[(type(obj).KIND, obj.key())] = updated.metadata.resource_version

    def stats_summary(self) -> dict:
        """Summary-API analog (ref: pkg/kubelet/server/stats/summary.go):
        node totals + per-pod per-container point-in-time usage, served at
        the kubelet server's /stats/summary."""
        pods_out = []
        node_cpu, node_mem = 0.0, 0.0
        for pod in self.pods.list():
            with self._lock:
                cids = {
                    name: cid
                    for (uid, name), cid in self._containers.items()
                    if uid == pod.metadata.uid
                }
            containers = []
            for cname, cid in sorted(cids.items()):
                stats = self._container_usage(pod.metadata.uid, cname, cid)
                node_cpu += stats.get("cpu", 0.0)
                node_mem += stats.get("memory", 0.0)
                containers.append({
                    "name": cname,
                    "cpu_cores": round(stats.get("cpu", 0.0), 4),
                    "memory_bytes": int(stats.get("memory", 0.0)),
                })
            entry = {
                "pod": pod.key(),
                "containers": containers,
            }
            pod_cg = self.container_manager.pod_stats(pod.metadata.uid)
            if pod_cg is not None:
                entry["cgroup"] = {
                    "cpu_cores": round(pod_cg["cpu"], 4),
                    "memory_bytes": int(pod_cg["memory"]),
                }
            pods_out.append(entry)
        return {
            "node": {
                "nodeName": self.node_name,
                "capacity": dict(self.capacity),
                "cpu_cores": round(node_cpu, 4),
                "memory_bytes": int(node_mem),
            },
            "pods": pods_out,
        }

    def _publish_metrics(self):
        """Resource-metrics pipeline, one hop: runtime stats → PodMetrics /
        NodeMetrics objects (ref: cadvisor → /stats/summary
        (server/stats/summary.go) → metrics-server → metrics.k8s.io)."""
        now = now_iso()
        node_cpu, node_mem = 0.0, 0.0
        my_pods = [p for p in self.pods.list()
                   if p.spec.node_name == self.node_name]
        # the custom-metrics hop rides the same cadence: diff the
        # annotated-pod set against the running scrape threads (no I/O
        # here — the scrapes themselves live on per-pod threads)
        self.pod_scraper.reconcile(my_pods)
        for pod in my_pods:
            with self._lock:
                cids = {
                    name: cid
                    for (uid, name), cid in self._containers.items()
                    if uid == pod.metadata.uid
                }
            if not cids:
                continue
            pm = t.PodMetrics(timestamp=now)
            pm.metadata.name = pod.metadata.name
            pm.metadata.namespace = pod.metadata.namespace
            for cname, cid in sorted(cids.items()):
                stats = self._container_usage(pod.metadata.uid, cname, cid)
                node_cpu += stats.get("cpu", 0.0)
                node_mem += stats.get("memory", 0.0)
                pm.containers.append(
                    t.ContainerMetrics(name=cname, usage=self._fmt_usage(stats))
                )
            self._upsert_metrics(self.cs.podmetrics, pm, pod.metadata.namespace)
        nm = t.NodeMetrics(
            timestamp=now, usage=self._fmt_usage({"cpu": node_cpu, "memory": node_mem})
        )
        nm.metadata.name = self.node_name
        self._upsert_metrics(self.cs.nodemetrics, nm)

    # ------------------------------------------------------------ pod source

    def _enqueue(self, pod: t.Pod):
        self._queue.add(pod.key())

    def _load_static_pods(self):
        """File source (ref: kubelet.go:277-321): manifests in a directory
        become pods bound to this node — how control-plane self-hosting runs."""
        import yaml

        for fname in sorted(os.listdir(self.static_pod_dir)):
            if not fname.endswith((".json", ".yaml", ".yml")):
                continue
            path = os.path.join(self.static_pod_dir, fname)
            try:
                with open(path) as f:
                    data = yaml.safe_load(f) if fname.endswith((".yaml", ".yml")) else json.load(f)
                pod = global_scheme.decode(data)
                pod.spec.node_name = self.node_name
                pod.metadata.annotations[t.STATIC_POD_ANNOTATION] = "true"
                try:
                    self.cs.pods.create(pod)
                except ApiError:
                    pass  # already mirrored
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _tick_all(self):
        for pod in self.pods.list():
            self._queue.add(pod.key())

    # ----------------------------------------------------------------- PLEG

    def _pleg_relist(self):
        """1s relist-and-diff (ref: pleg/generic.go:182): container state
        changes enqueue their pod for sync."""
        current: Dict[str, str] = {}
        sandbox_pod: Dict[str, str] = {}
        for sb in self.runtime.list_pod_sandboxes():
            sandbox_pod[sb.id] = f"{sb.pod_namespace}/{sb.pod_name}"
        for c in self.runtime.list_containers():
            current[c.id] = c.state
            old = self._pleg_state.get(c.id)
            if old != c.state:
                pod_key = sandbox_pod.get(c.sandbox_id)
                if pod_key:
                    self._queue.add(pod_key)
        self._pleg_state = current

    # --------------------------------------------------------- sync workers

    def _sync_worker(self):
        while not self._stop.is_set():
            key = self._queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                pod = self.pods.get(key)
                if pod is None:
                    self._cleanup_missing(key)
                else:
                    self.sync_pod(pod)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            finally:
                self._queue.done(key)

    def _cleanup_missing(self, key: str):
        """Pod vanished from the API: tear down any leftover runtime state."""
        ns, name = key.split("/", 1)
        for sb in self.runtime.list_pod_sandboxes():
            if sb.pod_namespace == ns and sb.pod_name == name:
                self.runtime.remove_pod_sandbox(sb.id)
                with self._lock:
                    self._sandboxes.pop(sb.pod_uid, None)
                    for k in [k for k in self._containers if k[0] == sb.pod_uid]:
                        self._containers.pop(k, None)
                self.device_manager.forget_pod(sb.pod_uid)
                self.volume_manager.teardown_pod(sb.pod_uid)
                self.container_manager.remove_pod_cgroup(sb.pod_uid)
                self.cpu_manager.release_pod(sb.pod_uid)
                self._prune_pod_state(sb.pod_uid)

    # -------------------------------------------------------------- syncPod

    def sync_pod(self, pod: t.Pod):
        """ref: kubelet.go:1441 syncPod."""
        uid = pod.metadata.uid
        if pod.metadata.deletion_timestamp:
            self._terminate_pod(pod)
            return
        if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
            self.prober.remove_pod(uid)  # finished pods are never probed
            self._ensure_stopped(pod)
            return

        verdict, reason = self._admit(pod)
        if verdict == "fail":
            self._set_failed(pod, "AdmissionError", reason)
            return
        if verdict == "wait":
            return  # infrastructure warming up; sync ticker retries

        # Volumes before containers (ref: syncPod order — WaitForAttachAndMount
        # precedes runtime SyncPod).  Unready sources wait; broken ones fail.
        try:
            self.volume_manager.mount_pod(pod)
            self.volume_manager.refresh_pod(pod)
        except VolumeNotReady as e:
            if uid not in self._mount_warned:
                self._mount_warned.add(uid)
                self.recorder.event(pod, "Warning", "FailedMount", str(e))
            return  # sync ticker retries
        except VolumeError as e:
            self._set_failed(pod, "FailedMount", str(e))
            return

        # idempotent (one-time per incarnation): also re-registers adopted
        # pods' cgroups after a kubelet restart so stats/OOM detection work
        self.container_manager.ensure_pod_cgroup(pod)

        sandbox_id = self._ensure_sandbox(pod)
        # init containers run sequentially to completion BEFORE any app
        # container starts (ref kuberuntime_manager.go computePodActions:
        # next init container gates the whole pod)
        init_state = self._sync_init_containers(pod, sandbox_id)
        if init_state == "failed":
            return  # _set_failed already PUT the terminal status
        if init_state == "wait":
            self._sync_status(pod)
            return
        self._sync_containers(pod, sandbox_id)
        self.prober.ensure_pod(pod)
        self._sync_status(pod)

    def _sync_init_containers(self, pod: t.Pod, sandbox_id: str) -> str:
        """Advance the init-container sequence one sync at a time.
        Returns "done" (all exited 0), "wait" (in progress / backoff), or
        "failed" (terminal status already written)."""
        uid = pod.metadata.uid
        for container in pod.spec.init_containers:
            ckey = (uid, container.name)
            with self._lock:
                cid = self._containers.get(ckey)
            record = self.runtime.container_status(cid) if cid else None
            if record is not None and record.state == CONTAINER_RUNNING:
                return "wait"  # wait for it; ticker re-syncs
            if record is not None and record.state not in (
                    CONTAINER_RUNNING, CONTAINER_EXITED):
                # CREATED (kubelet died between create and start, record
                # adopted on restart): start it — falling through here
                # would skip the init container entirely
                try:
                    self.runtime.start_container(record.id)
                except Exception as e:  # noqa: BLE001
                    self.recorder.event(pod, "Warning", "FailedStart",
                                        f"init {container.name}: {e}")
                return "wait"
            if record is not None and record.state == CONTAINER_EXITED:
                if record.exit_code == 0:
                    continue  # done; on to the next init container
                # failed init container: Never fails the pod; otherwise the
                # SAME instance restarts with crash backoff (ref: init
                # containers restart under OnFailure/Always alike)
                if pod.spec.restart_policy == "Never":
                    self._set_failed(
                        pod, "InitContainerError",
                        f"init container {container.name} exited "
                        f"{record.exit_code}")
                    return "failed"
                now = time.monotonic()
                with self._lock:
                    n = self._restarts.get(ckey, 0)
                    if now < self._restart_at.get(ckey, 0.0):
                        return "wait"  # backoff; ticker retries
                    self._restarts[ckey] = n + 1
                    self._restart_at[ckey] = now + min(
                        self.restart_backoff_base * (2**n), 300.0)
                self.runtime.remove_container(record.id)
                self.recorder.event(
                    pod, "Normal", "Restarting",
                    f"init container {container.name} exited "
                    f"{record.exit_code}; restarting")
                record = None
            if record is None:
                with self._lock:
                    if time.monotonic() < self._restart_at.get(ckey, 0.0):
                        return "wait"
                try:
                    config = self._container_config(pod, container)
                except VolumeNotReady:
                    return "wait"  # ticker retries once sources appear
                except CPUExhaustedError as e:
                    # exclusive-cpu exhaustion: same FailedStart + backoff as
                    # app containers — releases free cpus, the ticker retries
                    now = time.monotonic()
                    with self._lock:
                        n = self._restarts.get(ckey, 0)
                        self._restarts[ckey] = n + 1
                        self._restart_at[ckey] = now + min(
                            self.restart_backoff_base * (2**n), 300.0)
                    self.recorder.event(pod, "Warning", "FailedStart",
                                        f"init {container.name}: {e}")
                    return "wait"
                except VolumeError as e:
                    self._set_failed(pod, "CreateContainerConfigError", str(e))
                    return "failed"
                cid = None  # the looked-up id is stale past this point
                try:
                    if hasattr(self.runtime, "images"):
                        # imagePullPolicy applies to init containers too
                        # (AlwaysPullImages admission sets it on them)
                        policy = container.image_pull_policy or "IfNotPresent"
                        present = self.runtime.images.image_present(
                            container.image)
                        if policy == "Always" or (policy != "Never"
                                                  and not present):
                            self.runtime.images.pull_image(container.image)
                    cid = self.runtime.create_container(sandbox_id, config)
                    self.runtime.start_container(cid)
                    with self._lock:
                        self._containers[ckey] = cid
                    self.recorder.event(
                        pod, "Normal", "Started",
                        f"init container {container.name}")
                except Exception as e:  # noqa: BLE001
                    if cid is not None:
                        try:
                            self.runtime.remove_container(cid)
                        except (OSError, RuntimeError, KeyError):
                            pass  # cleanup of a half-created container is best-effort
                    if self._is_terminal_config_error(e):
                        self._set_failed(pod, "CreateContainerConfigError",
                                         f"init {container.name}: {e}")
                        return "failed"
                    now = time.monotonic()
                    with self._lock:
                        n = self._restarts.get(ckey, 0)
                        self._restarts[ckey] = n + 1
                        self._restart_at[ckey] = now + min(
                            self.restart_backoff_base * (2**n), 300.0)
                    self.recorder.event(pod, "Warning", "FailedStart",
                                        f"init {container.name}: {e}")
                return "wait"  # started (or failed to): wait for next sync
        return "done"

    @staticmethod
    def _is_terminal_config_error(e: Exception) -> bool:
        """Start failures that can NEVER succeed by retrying: an identity
        request the host cannot honor (non-root kubelet, missing setpriv —
        runtime.py _wrap_with_user; the native runtime raises the same
        wording over the CRI socket).  These must fail the pod terminally,
        not back off forever."""
        return isinstance(e, PermissionError) or \
            "requires a root" in str(e)

    ADMISSION_GRACE_SECONDS = 30.0

    @staticmethod
    def _pod_trace_id(pod: t.Pod) -> str:
        return (pod.metadata.annotations or {}).get(t.TRACE_ID_ANNOTATION, "")

    def _stamp_admitted(self, pod: t.Pod):
        """Persist the device-admission instant for the pod-startup SLI
        decomposition (utils/slo).  Once per pod (a kubelet restart must
        not overwrite the original stamp); best-effort — SLI bookkeeping
        must never block a pod from starting."""
        if t.ADMITTED_AT_ANNOTATION in (pod.metadata.annotations or {}):
            return
        try:
            self.cs.pods.patch(
                pod.metadata.name,
                {"metadata": {"annotations": {
                    t.ADMITTED_AT_ANNOTATION: f"{time.time():.6f}"}}},  # ktpulint: ignore[KTPU005] cross-process SLI wall stamp
                namespace=pod.metadata.namespace,
            )
        except (ApiError, OSError):
            pass

    def _admit(self, pod: t.Pod) -> Tuple[str, str]:
        """Returns ('ok'|'wait'|'fail', reason).  Retriable denials (device
        manager warming up after kubelet/plugin restart) wait up to
        ADMISSION_GRACE_SECONDS before failing the pod."""
        uid = pod.metadata.uid
        with self._lock:
            cached = self._admitted.get(uid)
        if cached is not None:
            return cached
        # the TPU path's signature span: scheduler-assigned device IDs
        # verified against local inventory + the plugin's AdmitPod RPC
        span_name = ("kubelet.device_allocation"
                     if pod.spec.extended_resources else "kubelet.admit")
        with self.spans.start_span(span_name,
                                   trace_id=self._pod_trace_id(pod),
                                   pod=pod.key()) as sp:
            result = self.device_manager.admit_pod(pod)
            if result.allowed:
                with self._lock:
                    self._admitted[uid] = ("ok", "")
                self._stamp_admitted(pod)
                return "ok", ""
            sp.annotate(denied=result.reason, retriable=result.retriable)
        if result.retriable:
            with self._lock:
                first = self._admit_first_seen.setdefault(uid, time.monotonic())
            if time.monotonic() - first < self.ADMISSION_GRACE_SECONDS:
                return "wait", result.reason
        self.recorder.event(pod, "Warning", "AdmissionError", result.reason)
        with self._lock:
            self._admitted[uid] = ("fail", result.reason)
        return "fail", result.reason

    def _ensure_sandbox(self, pod: t.Pod) -> str:
        uid = pod.metadata.uid
        with self._lock:
            sid = self._sandboxes.get(uid)
        if sid is not None:
            return sid
        with self.spans.start_span("kubelet.create_sandbox",
                                   trace_id=self._pod_trace_id(pod),
                                   pod=pod.key()):
            sid = self.runtime.run_pod_sandbox(
                pod.metadata.name, pod.metadata.namespace, uid,
                labels={"pod-uid": uid},
            )
        with self._lock:
            self._sandboxes[uid] = sid
        return sid

    def _resolv_conf_path(self, namespace: str) -> str:
        """Per-namespace resolv.conf under the volume root (the search
        path differs per namespace), written once and reused."""
        d = os.path.join(self.volume_manager.root, "resolv")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{namespace}.conf")
        content = self.cluster_dns.resolv_conf(namespace)
        try:
            with open(path) as f:
                if f.read() == content:
                    return path
        except OSError:
            pass
        faultline.check("kubelet.statefile")  # node-local state write
        with open(path, "w") as f:
            f.write(content)
        return path

    def _container_config(self, pod: t.Pod, container: t.Container) -> ContainerConfig:
        """GenerateRunContainerOptions (ref kubelet_pods.go:468): pod env
        (incl. valueFrom/envFrom/downward API) + volume mounts +
        device-plugin injection merged into the CRI config."""
        env = self.volume_manager.make_environment(pod, container)
        # in-pod API access: the mounted SA token + this endpoint is the
        # KUBERNETES_SERVICE_HOST/PORT analog
        env.setdefault("KTPU_APISERVER", self.cs.api.url)
        dns_mount = None
        if self.cluster_dns is not None:
            # cluster DNS wiring (ref --cluster-dns): the resolver address
            # rides env for library clients, and the pod's resolv.conf is
            # bind-mounted so glibc's gethostbyname('redis-master') just
            # works inside the mount namespace
            env.setdefault("KTPU_DNS_SERVER", self.cluster_dns.ip)
            ns = pod.metadata.namespace or "default"
            dns_mount = {"name": "cluster-dns-resolv",
                         "host_path": self._resolv_conf_path(ns),
                         "container_path": "/etc/resolv.conf",
                         "read_only": True}
        spec = self.device_manager.init_container(pod, container)
        env.update(spec.envs)
        devices = [vars(d) for d in spec.devices]
        mounts = self.volume_manager.mounts_for_container(pod, container)
        mounts += [vars(m) for m in spec.mounts]
        if dns_mount is not None:
            mounts.append(dns_mount)
        annotations = dict(spec.annotations)
        # securityContext (ref pkg/securitycontext + kuberuntime's
        # verifyRunAsNonRoot): resolve the effective identity, refuse a
        # runAsNonRoot container that would land on uid 0, and gate raw
        # /dev hostPath mounts on privileged — unprivileged pods get TPU
        # chips ONLY through the device-plugin allocation path
        sc = t.effective_security_context(pod, container)
        if sc.run_as_non_root:
            uid = sc.run_as_user
            if uid is None:
                # No numeric uid anywhere in the spec: the container will
                # exec as the RUNTIME's identity — this framework's analog
                # of the image USER that upstream kuberuntime resolves for
                # verifyRunAsNonRoot.  Ask the runtime (over the CRI
                # capabilities RPC for a remote one); the kubelet's own
                # euid is NOT a substitute — kubelet and runtime daemon
                # can run as different users.  Unknown identity fails
                # CLOSED: admitting would risk silently running as root.
                uid = getattr(self.runtime, "default_uid", None)
                if uid is None:
                    # fail-closed either way, but distinguish WHY: a remote
                    # runtime that hasn't answered capabilities yet is
                    # transient (kubelet and runtime start concurrently by
                    # design) — defer; one that ANSWERED without an
                    # identity (version skew) will never change its mind —
                    # fail the pod with a real error, don't livelock
                    if getattr(self.runtime, "identity_known", True):
                        raise VolumeError(
                            f"container {container.name}: runAsNonRoot is "
                            f"set with no runAsUser and the runtime does "
                            f"not report its identity — refusing rather "
                            f"than risk root")
                    raise VolumeNotReady(
                        f"container {container.name}: runAsNonRoot is set "
                        f"with no runAsUser and the runtime's identity is "
                        f"not known yet — deferring rather than risk root")
            if uid == 0:
                raise VolumeError(
                    f"container {container.name}: runAsNonRoot is set but "
                    f"the container would run as root"
                    f"{' (runtime identity)' if sc.run_as_user is None else ''}")
        if not sc.privileged:
            from ..utils.hostpath import is_under, normalize_abs

            for m in mounts:
                host = normalize_abs(m.get("host_path") or "")
                if is_under(host, "/dev"):
                    raise VolumeError(
                        f"container {container.name}: hostPath {host!r} "
                        f"requires privileged: true (device access is "
                        f"granted via google.com/tpu requests, not raw "
                        f"/dev mounts)")
        return ContainerConfig(
            name=container.name,
            image=container.image,
            command=list(container.command),
            args=list(container.args),
            env=env,
            working_dir=container.working_dir,
            devices=devices,
            mounts=mounts,
            annotations=annotations,
            cgroup_procs_files=self.container_manager.container_join_files(
                pod, container),
            cpuset=sorted(self.cpu_manager.cpuset_for_container(pod, container)
                          or []),
            run_as_user=sc.run_as_user,
            run_as_group=sc.run_as_group,
            privileged=bool(sc.privileged),
        )

    def _sync_containers(self, pod: t.Pod, sandbox_id: str):
        uid = pod.metadata.uid
        for container in pod.spec.containers:
            ckey = (uid, container.name)
            with self._lock:
                cid = self._containers.get(ckey)
            record = self.runtime.container_status(cid) if cid else None
            if record is not None and record.state == CONTAINER_RUNNING:
                if self.prober.liveness_failed(uid, container.name):
                    # failing liveness => kill; the restart path below brings
                    # it back with backoff (ref: prober result -> syncPod kill)
                    self.recorder.event(
                        pod, "Warning", "Unhealthy",
                        f"liveness probe failed for {container.name}; restarting",
                    )
                    self.runtime.stop_container(record.id, timeout=2.0)
                    self.prober.restart_container(uid, container.name)
                    record = self.runtime.container_status(record.id)
                    if record is None or record.state == CONTAINER_RUNNING:
                        continue
                else:
                    continue
            if record is not None and record.state == CONTAINER_EXITED:
                if not self._should_restart(pod, record.exit_code):
                    continue
                now = time.monotonic()
                with self._lock:
                    n = self._restarts.get(ckey, 0)
                    next_at = self._restart_at.get(ckey, 0.0)
                if now < next_at:
                    continue  # backoff; ticker retries
                with self._lock:
                    self._restarts[ckey] = n + 1
                    self._restart_at[ckey] = now + min(
                        self.restart_backoff_base * (2**n), 300.0
                    )
                self.runtime.remove_container(record.id)
                # probe state belongs to the dead instance — reset so stale
                # failures aren't charged to the replacement
                self.prober.restart_container(uid, container.name)
                self.recorder.event(
                    pod, "Normal", "Restarting",
                    f"container {container.name} exited {record.exit_code}; restarting",
                )
            # create + start (start failures back off like crash restarts and
            # must not leak the half-created container record)
            with self._lock:
                if time.monotonic() < self._restart_at.get(ckey, 0.0):
                    continue
            cid = None
            try:
                config = self._container_config(pod, container)
            except CPUExhaustedError as e:
                # exclusive-cpu pool exhausted (ref policy_static.go fails
                # the container): backoff + retry — releases free cpus
                with self._lock:
                    n = self._restarts.get(ckey, 0)
                    self._restarts[ckey] = n + 1
                    self._restart_at[ckey] = time.monotonic() + min(
                        self.restart_backoff_base * (2**n), 300.0
                    )
                self.recorder.event(
                    pod, "Warning", "FailedStart",
                    f"container {container.name}: {e}",
                )
                continue
            except VolumeNotReady as e:
                # transient (envFrom source not yet visible): per-tick retry,
                # not the exponential FailedStart backoff
                if uid not in self._mount_warned:
                    self._mount_warned.add(uid)
                    self.recorder.event(pod, "Warning", "FailedMount", str(e))
                continue
            except VolumeError as e:
                # permanent config error (missing key): fail the pod like the
                # reference's CreateContainerConfigError terminal path
                self._set_failed(pod, "CreateContainerConfigError", str(e))
                return
            try:
                if hasattr(self.runtime, "images"):
                    # imagePullPolicy (ref kuberuntime_container.go:88):
                    # Always re-pulls; Never skips; default pulls if absent
                    policy = container.image_pull_policy or "IfNotPresent"
                    present = self.runtime.images.image_present(container.image)
                    if policy == "Always" or (policy != "Never" and not present):
                        self.runtime.images.pull_image(container.image)
                # the span covers the /dev/accel* injection spec landing in
                # the CRI create — the tail of the device_allocation path
                with self.spans.start_span(
                        "kubelet.start_container",
                        trace_id=self._pod_trace_id(pod), pod=pod.key(),
                        container=container.name,
                        devices=len(config.devices)):
                    cid = self.runtime.create_container(sandbox_id, config)
                    self.runtime.start_container(cid)
                with self._lock:
                    self._containers[ckey] = cid
                self.recorder.event(
                    pod, "Normal", "Started", f"container {container.name} started"
                )
            except Exception as e:  # noqa: BLE001
                if cid is not None:
                    try:
                        self.runtime.remove_container(cid)
                    except (OSError, RuntimeError, KeyError):
                        pass  # cleanup of a half-created container is best-effort
                if self._is_terminal_config_error(e):
                    self._set_failed(pod, "CreateContainerConfigError",
                                     f"container {container.name}: {e}")
                    return
                with self._lock:
                    n = self._restarts.get(ckey, 0)
                    self._restarts[ckey] = n + 1
                    self._restart_at[ckey] = time.monotonic() + min(
                        self.restart_backoff_base * (2**n), 300.0
                    )
                self.recorder.event(
                    pod, "Warning", "FailedStart",
                    f"container {container.name}: {e}",
                )

    @staticmethod
    def _should_restart(pod: t.Pod, exit_code: Optional[int]) -> bool:
        policy = pod.spec.restart_policy
        if policy == "Always":
            return True
        if policy == "OnFailure":
            return exit_code not in (0, None)
        return False

    # ------------------------------------------------------------- teardown

    def _terminate_pod(self, pod: t.Pod):
        """Graceful deletion: stop containers, remove sandbox, then force
        delete so the API object goes away (the reference's kubelet sends
        the final grace-0 delete)."""
        uid = pod.metadata.uid
        with self._lock:
            sid = self._sandboxes.get(uid)
        if sid is not None:
            self.runtime.stop_pod_sandbox(sid)
            self.runtime.remove_pod_sandbox(sid)
            with self._lock:
                self._sandboxes.pop(uid, None)
                for k in [k for k in self._containers if k[0] == uid]:
                    self._containers.pop(k, None)
        self.device_manager.forget_pod(uid)
        self.volume_manager.teardown_pod(uid)
        self.container_manager.remove_pod_cgroup(uid)
        self.cpu_manager.release_pod(uid)
        self._prune_pod_state(uid)
        try:
            self.cs.pods.delete(
                pod.metadata.name, pod.metadata.namespace, grace_seconds=0
            )
        except ApiError:
            pass

    def _prune_pod_state(self, uid: str):
        """Drop every per-pod bookkeeping entry (unbounded growth otherwise
        under Job-style pod churn)."""
        self.prober.remove_pod(uid)
        self._mount_warned.discard(uid)
        with self._lock:
            self._oom_baseline.pop(uid, None)
            for k in [k for k in self._oom_marked if k[0] == uid]:
                self._oom_marked.discard(k)
            self._admitted.pop(uid, None)
            self._admit_first_seen.pop(uid, None)
            self._last_status.pop(uid, None)
            for k in [k for k in self._restarts if k[0] == uid]:
                self._restarts.pop(k, None)
            for k in [k for k in self._restart_at if k[0] == uid]:
                self._restart_at.pop(k, None)

    def _ensure_stopped(self, pod: t.Pod):
        uid = pod.metadata.uid
        with self._lock:
            sid = self._sandboxes.get(uid)
        if sid is not None:
            self.runtime.stop_pod_sandbox(sid)

    def _set_failed(self, pod: t.Pod, reason: str, message: str):
        fresh = pod.clone()  # clone-before-mutate: pod is an informer snapshot
        fresh.status.phase = t.POD_FAILED
        fresh.status.reason = reason
        fresh.status.message = message
        try:
            self.cs.pods.update_status(fresh)
        except ApiError:
            pass

    # --------------------------------------------------------------- status

    def _compute_status(self, pod: t.Pod) -> t.PodStatus:
        uid = pod.metadata.uid
        status = t.PodStatus()
        status.host_ip = self.node_name
        status.pod_ip = "127.0.0.1"
        status.start_time = pod.status.start_time or now_iso()
        statuses: List[t.ContainerStatus] = []
        running = exited_ok = exited_bad = waiting = 0
        for container in pod.spec.containers:
            ckey = (uid, container.name)
            with self._lock:
                cid = self._containers.get(ckey)
                restarts = self._restarts.get(ckey, 0)
            record = self.runtime.container_status(cid) if cid else None
            cs = t.ContainerStatus(
                name=container.name, image=container.image, restart_count=restarts
            )
            if record is None:
                waiting += 1
                cs.state.waiting = t.ContainerStateWaiting(reason="ContainerCreating")
            elif record.state == CONTAINER_RUNNING:
                running += 1
                cs.ready = self.prober.is_ready(uid, container.name)
                cs.container_id = record.id
                cs.state.running = t.ContainerStateRunning(
                    started_at=_iso(record.started_at)
                )
            elif record.state == CONTAINER_EXITED:
                cs.container_id = record.id
                reason = "Completed" if record.exit_code == 0 else "Error"
                # SIGKILL + a NEW kill recorded in the pod's memory cgroup =
                # the kernel OOM killer enforced the limit.  The counter is
                # cumulative, so each kill is attributed to exactly one
                # container instance — a historic OOM must not relabel later
                # kubelet-initiated SIGKILLs.
                if record.exit_code in (137, -9):
                    ckey2 = (uid, record.id)
                    with self._lock:
                        if ckey2 in self._oom_marked:
                            reason = "OOMKilled"
                        else:
                            count = self.container_manager.oom_kill_count(uid)
                            if count > self._oom_baseline.get(uid, 0):
                                self._oom_baseline[uid] = count
                                self._oom_marked.add(ckey2)
                                reason = "OOMKilled"
                cs.state.terminated = t.ContainerStateTerminated(
                    exit_code=record.exit_code or 0,
                    reason=reason,
                    started_at=_iso(record.started_at),
                    finished_at=_iso(record.finished_at),
                )
                if record.exit_code == 0:
                    exited_ok += 1
                else:
                    exited_bad += 1
            else:
                waiting += 1
                cs.state.waiting = t.ContainerStateWaiting(reason="Created")
            statuses.append(cs)
        status.container_statuses = statuses
        total = len(pod.spec.containers)
        policy = pod.spec.restart_policy
        if running == total and total > 0:
            status.phase = t.POD_RUNNING
        elif exited_ok == total and policy != "Always":
            status.phase = t.POD_SUCCEEDED
        elif exited_bad > 0 and policy == "Never":
            status.phase = t.POD_FAILED
        elif running > 0:
            status.phase = t.POD_RUNNING
        else:
            status.phase = t.POD_PENDING
        ready = all(c.ready for c in statuses) and status.phase == t.POD_RUNNING
        status.conditions = [
            t.PodCondition(
                type="Ready",
                status="True" if ready else "False",
                last_transition_time=now_iso(),
            ),
            t.PodCondition(type="PodScheduled", status="True"),
        ]
        return status

    def _sync_status(self, pod: t.Pod):
        """statusManager syncBatch (ref status_manager.go:399): PUT only on
        change (conditions' timestamps excluded from the comparison)."""
        status = self._compute_status(pod)
        from ..machinery.scheme import to_dict

        desired = to_dict(status)
        comparable = json.dumps(
            {k: v for k, v in desired.items() if k != "conditions"}, sort_keys=True
        )
        uid = pod.metadata.uid
        with self._lock:
            if self._last_status.get(uid) == comparable:
                return
        fresh = pod.clone()  # clone-before-mutate: pod is an informer snapshot
        fresh.status = status
        try:
            # unified retry policy (client/retry): transient failures —
            # overload sheds past the transport's own budget, 5xx, link
            # faults — back off with full jitter and retry in place;
            # terminal ones fall through to the handlers below
            _retry.call_with_retries(
                lambda: self.cs.pods.update_status(fresh),
                steps=3, reason="status_sync")
            with self._lock:
                self._last_status[uid] = comparable
        except NotFound:
            pass
        except Conflict:
            # stale informer copy (e.g. the SLI admitted-at patch just
            # bumped the rv): the next sync retries from the fresh object
            pass
        except (ConnectionError, TimeoutError):
            # transport still down after the retry budget: the next sync
            # tick retries from a fresh informer snapshot
            pass
        except ApiError:
            traceback.print_exc()


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts)) if ts else ""
