"""Node-pressure eviction manager.

Ref: pkg/kubelet/eviction/{eviction_manager.go,helpers.go} — observe
memory/disk signals against thresholds, set node pressure conditions, and
evict pods lowest-QoS-first until the signal clears. QoS classes follow the
reference: BestEffort (no requests) < Burstable (requests < limits) <
Guaranteed (requests == limits for every resource). On a TPU node the main
customer is host RAM: a runaway input pipeline must be evicted before it
OOMs the libtpu runtime.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

from ..api import types as t
from ..utils import locksan
from ..utils.quantity import parse_quantity

QOS_GUARANTEED = "Guaranteed"
QOS_BURSTABLE = "Burstable"
QOS_BESTEFFORT = "BestEffort"

_QOS_EVICTION_ORDER = {QOS_BESTEFFORT: 0, QOS_BURSTABLE: 1, QOS_GUARANTEED: 2}


def qos_class(pod: t.Pod) -> str:
    """ref: pkg/apis/core/v1/helper/qos/qos.go GetPodQOS. Requests default
    to limits when unset (the apiserver's defaulting), so limits-only pods
    are Guaranteed, not Burstable."""
    any_resources = False
    guaranteed = True
    for c in pod.spec.containers:
        req, lim = c.resources.requests or {}, c.resources.limits or {}
        if req or lim:
            any_resources = True
        for res in ("cpu", "memory"):
            limit = lim.get(res)
            request = req.get(res, limit)  # defaulting: request := limit
            if limit is None or request is None:
                guaranteed = False
            elif parse_quantity(request) != parse_quantity(limit):
                guaranteed = False
    if not any_resources:
        return QOS_BESTEFFORT
    return QOS_GUARANTEED if guaranteed else QOS_BURSTABLE


def default_signals() -> Dict[str, float]:
    """Real node signals: fraction available (0..1) per resource."""
    signals = {}
    try:
        total = avail = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
        if total and avail is not None:
            signals["memory.available"] = avail / total
    except OSError:
        pass
    try:
        st = os.statvfs("/")
        signals["nodefs.available"] = st.f_bavail / max(st.f_blocks, 1)
    except OSError:
        pass
    return signals


class EvictionManager:
    """Synchronize loop (ref: eviction_manager.go synchronize): when a signal
    drops under its threshold, evict the best candidate and set the matching
    node condition until pressure clears (with a min-reclaim hysteresis via
    pressure transition period)."""

    SIGNAL_CONDITIONS = {
        "memory.available": "MemoryPressure",
        "nodefs.available": "DiskPressure",
    }

    def __init__(
        self,
        thresholds: Optional[Dict[str, float]] = None,  # fraction available
        signals_fn: Callable[[], Dict[str, float]] = default_signals,
        evict_fn: Optional[Callable[[t.Pod, str], None]] = None,
        list_pods: Optional[Callable[[], List[t.Pod]]] = None,
        pressure_transition_period: float = 10.0,
    ):
        self.thresholds = thresholds or {
            "memory.available": 0.05, "nodefs.available": 0.10,
        }
        self.signals_fn = signals_fn
        self.evict_fn = evict_fn
        self.list_pods = list_pods
        self.pressure_transition_period = pressure_transition_period
        self._pressure_until: Dict[str, float] = {}
        self._lock = locksan.make_lock("EvictionManager._lock")

    # ------------------------------------------------------------ conditions

    def node_conditions(self) -> List[t.NodeCondition]:
        """Pressure conditions for the node status (heartbeat merges these)."""
        now = time.monotonic()
        out = []
        with self._lock:
            for signal, cond_type in self.SIGNAL_CONDITIONS.items():
                under = self._pressure_until.get(signal, 0.0) > now
                out.append(
                    t.NodeCondition(
                        type=cond_type,
                        status="True" if under else "False",
                        reason="KubeletHasInsufficient" + cond_type.replace("Pressure", "")
                        if under else "KubeletHasSufficient" + cond_type.replace("Pressure", ""),
                    )
                )
        return out

    # ------------------------------------------------------------- synchronize

    def synchronize(self) -> List[str]:
        """One pass; returns names of evicted pods."""
        signals = self.signals_fn()
        evicted: List[str] = []
        now = time.monotonic()
        for signal, threshold in self.thresholds.items():
            value = signals.get(signal)
            if value is None:
                continue
            if value >= threshold:
                continue
            with self._lock:
                self._pressure_until[signal] = now + self.pressure_transition_period
            # exclude this pass's victims: their Failed status hasn't
            # propagated to the lister yet, and double-evicting one pod
            # reclaims nothing for the second signal
            victim = self._pick_victim(exclude=set(evicted))
            if victim is not None and self.evict_fn is not None:
                reason = (
                    f"node pressure: {signal} {value:.1%} below "
                    f"threshold {threshold:.1%}"
                )
                self.evict_fn(victim, reason)
                evicted.append(victim.metadata.name)
        return evicted

    def _pick_victim(self, exclude: Optional[set] = None) -> Optional[t.Pod]:
        """Rank: lowest QoS first, then newest (the reference ranks by usage
        over request; without per-pod usage attribution newest-first bounds
        the blast radius the same way)."""
        if self.list_pods is None:
            return None
        candidates = [
            p for p in self.list_pods()
            if p.status.phase == t.POD_RUNNING
            and not p.metadata.deletion_timestamp
            and p.metadata.name not in (exclude or set())
            # static/mirror control-plane pods are never pressure-evicted
            and p.spec.priority < 1_000_000
        ]
        if not candidates:
            return None
        candidates.sort(
            key=lambda p: (
                _QOS_EVICTION_ORDER[qos_class(p)],
                p.metadata.creation_timestamp,
            ),
        )
        best = candidates[0]
        # newest within the lowest class
        same_class = [
            p for p in candidates if qos_class(p) == qos_class(best)
        ]
        return max(same_class, key=lambda p: p.metadata.creation_timestamp)
