"""Container manager: cgroup QoS tree, per-pod resource enforcement, node
allocatable, and cgroup-ground-truth stats.

Ref: pkg/kubelet/cm/container_manager_linux.go:619 (the kubelet's cgroup
owner), cm/qos_container_manager_linux.go (the qos tree
kubepods/{burstable,besteffort}), cm/node_container_manager.go (node
allocatable = capacity - reserved), and eviction's QoS ranking.

Layout (node-unique so many kubelets on one host never collide):

    <cgroupfs>/ktpu/<node>/                  node root ("kubepods")
    <cgroupfs>/ktpu/<node>/guaranteed/pod<uid>/
    <cgroupfs>/ktpu/<node>/burstable/pod<uid>/
    <cgroupfs>/ktpu/<node>/besteffort/pod<uid>/

Backends:
- cgroup v2 (unified, preferred where memory+cpu controllers are delegated):
  memory.max / cpu.max, stats from memory.current + cpu.stat.
- cgroup v1 (hybrid hosts — this environment): memory and cpu hierarchies
  managed in parallel; memory.limit_in_bytes / cpu.cfs_quota_us, stats from
  memory.usage_in_bytes + cpuacct.usage.  The kernel OOM killer enforces
  the memory limit (SIGKILL -> exit 137 -> OOMKilled status + restart).
- null (no writable cgroupfs): limits are bookkeeping only, stats fall back
  to the runtime's /proc sampling — FakeRuntime scale tests take this path.

Processes join their pod cgroup pre-exec (the child writes itself into
cgroup.procs between fork and exec), so grandchildren can never escape.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..utils.quantity import parse_quantity
from .eviction import QOS_BESTEFFORT, QOS_BURSTABLE, QOS_GUARANTEED, qos_class
from ..utils import faultline, locksan

CPU_PERIOD_US = 100_000


def parse_milli(v) -> int:
    return int(round(parse_quantity(v) * 1000))


def pod_resource_totals(pod: t.Pod) -> Tuple[Optional[int], Optional[int]]:
    """(cpu_milli_limit, memory_bytes_limit) summed over containers; None
    when any container is unbounded for that resource (pod-level limit is
    only enforceable if every container carries one — ref qos cgroup calc)."""
    cpu_m = 0
    mem = 0
    cpu_ok = mem_ok = bool(pod.spec.containers)
    for c in pod.spec.containers:
        lim = c.resources.limits or {}
        if "cpu" in lim:
            cpu_m += parse_milli(lim["cpu"])
        else:
            cpu_ok = False
        if "memory" in lim:
            mem += int(parse_quantity(lim["memory"]))
        else:
            mem_ok = False
    return (cpu_m if cpu_ok else None), (mem if mem_ok else None)


class _Backend:
    """One cgroup filesystem flavor. Paths are relative to the node root."""

    name = "null"

    def ensure(self, rel: str):  # create the cgroup dir(s)
        pass

    def remove(self, rel: str):
        pass

    def set_limits(self, rel: str, cpu_milli: Optional[int], mem_bytes: Optional[int]):
        pass

    def procs_file(self, rel: str) -> Optional[str]:
        """cgroup.procs path a child process writes itself into (None = no
        enforcement)."""
        return None

    def stats(self, rel: str) -> Optional[Dict[str, float]]:
        """{"cpu_ns_total": N, "memory": bytes} or None."""
        return None

    def oom_kill_count(self, rel: str) -> int:
        return 0


class _V2Backend(_Backend):
    name = "cgroup2"

    def __init__(self, root: str, fs_root: str):
        self.root = root        # e.g. /sys/fs/cgroup/ktpu/<node>
        self.fs_root = fs_root  # the cgroup2 mount itself

    def _p(self, rel: str) -> str:
        return os.path.join(self.root, rel) if rel else self.root

    def ensure(self, rel: str):
        path = self._p(rel)
        os.makedirs(path, exist_ok=True)
        # v2 delegation: every ancestor must enable the controllers in its
        # subtree_control before children see memory.max/cpu.max (the "no
        # internal processes" rule keeps our intermediate dirs process-free,
        # so these writes are legal)
        cur = self.fs_root
        parts = os.path.relpath(path, self.fs_root).split(os.sep)
        for part in [None] + parts[:-1]:
            if part is not None:
                cur = os.path.join(cur, part)
            _write(os.path.join(cur, "cgroup.subtree_control"), "+memory +cpu")

    def remove(self, rel: str):
        try:
            os.rmdir(self._p(rel))
        except OSError:
            pass

    def set_limits(self, rel, cpu_milli, mem_bytes):
        base = self._p(rel)
        if mem_bytes is not None:
            _write(os.path.join(base, "memory.max"), str(mem_bytes))
        if cpu_milli is not None:
            quota = max(1000, cpu_milli * CPU_PERIOD_US // 1000)
            _write(os.path.join(base, "cpu.max"), f"{quota} {CPU_PERIOD_US}")

    def procs_file(self, rel):
        return os.path.join(self._p(rel), "cgroup.procs")

    def stats(self, rel):
        base = self._p(rel)
        try:
            mem = float(open(os.path.join(base, "memory.current")).read())
            cpu_us = 0.0
            for line in open(os.path.join(base, "cpu.stat")):
                if line.startswith("usage_usec"):
                    cpu_us = float(line.split()[1])
                    break
            return {"cpu_ns_total": cpu_us * 1000.0, "memory": mem}
        except OSError:
            return None

    def oom_kill_count(self, rel):
        # memory.events at the pod level is hierarchical on v2 (includes
        # container sub-cgroups)
        try:
            for line in open(os.path.join(self._p(rel), "memory.events")):
                if line.startswith("oom_kill"):
                    return int(line.split()[1])
        except OSError:
            pass
        return 0


class _V1Backend(_Backend):
    """Hybrid hosts: memory + cpu (+ separately-mounted cpuacct) v1
    hierarchies managed in parallel."""

    name = "cgroup1"

    def __init__(self, mem_root: str, cpu_root: str, cpuacct_root: str = ""):
        self.mem_root = mem_root
        self.cpu_root = cpu_root
        # cpuacct co-mounted with cpu -> empty; separate mount -> its own
        # hierarchy that processes must ALSO join for usage accounting
        self.cpuacct_root = cpuacct_root

    def _roots(self) -> List[str]:
        roots = [self.mem_root, self.cpu_root]
        if self.cpuacct_root:
            roots.append(self.cpuacct_root)
        return roots

    def _paths(self, rel: str) -> List[str]:
        return [os.path.join(r, rel) if rel else r for r in self._roots()]

    def ensure(self, rel: str):
        for p in self._paths(rel):
            os.makedirs(p, exist_ok=True)

    def remove(self, rel: str):
        for p in self._paths(rel):
            try:
                os.rmdir(p)
            except OSError:
                pass

    def set_limits(self, rel, cpu_milli, mem_bytes):
        mem_dir, cpu_dir = self._paths(rel)[:2]
        if mem_bytes is not None:
            _write(os.path.join(mem_dir, "memory.limit_in_bytes"), str(mem_bytes))
        if cpu_milli is not None:
            _write(os.path.join(cpu_dir, "cpu.cfs_period_us"), str(CPU_PERIOD_US))
            quota = max(1000, cpu_milli * CPU_PERIOD_US // 1000)
            _write(os.path.join(cpu_dir, "cpu.cfs_quota_us"), str(quota))

    def procs_file(self, rel):
        # the child joins memory; cpu joined via a second write (see
        # ContainerManager.preexec_files)
        return os.path.join(self._paths(rel)[0], "cgroup.procs")

    def procs_files(self, rel) -> List[str]:
        return [os.path.join(p, "cgroup.procs") for p in self._paths(rel)]

    def stats(self, rel):
        paths = self._paths(rel)
        mem_dir = paths[0]
        # cpuacct.usage lives in the cpuacct hierarchy when separately
        # mounted, else co-mounted with cpu
        acct_dir = paths[2] if len(paths) > 2 else paths[1]
        try:
            mem = float(open(os.path.join(mem_dir, "memory.usage_in_bytes")).read())
            acct = os.path.join(acct_dir, "cpuacct.usage")
            cpu_ns = float(open(acct).read()) if os.path.exists(acct) else 0.0
            return {"cpu_ns_total": cpu_ns, "memory": mem}
        except OSError:
            return None

    def oom_kill_count(self, rel):
        # memory.oom_control's oom_kill counter, not failcnt — failcnt also
        # ticks on reclaim-able limit hits that killed nothing.  v1 counters
        # are per-cgroup, so sum the pod dir and its container children
        # (the victim is charged where its tasks live).
        mem_dir = self._paths(rel)[0]
        dirs = [mem_dir]
        try:
            dirs += [os.path.join(mem_dir, d) for d in os.listdir(mem_dir)
                     if os.path.isdir(os.path.join(mem_dir, d))]
        except OSError:
            pass
        total = 0
        for d in dirs:
            try:
                for line in open(os.path.join(d, "memory.oom_control")):
                    if line.startswith("oom_kill "):
                        total += int(line.split()[1])
            except OSError:
                continue
        return total


def _write(path: str, value: str):
    try:
        # kubelet.statefile: an injected error exercises the same
        # best-effort path a missing kernel knob does (FaultInjected is
        # an OSError)
        faultline.check("kubelet.statefile")
        with open(path, "w") as f:
            f.write(value)
    except OSError:
        pass  # controller knob absent on this kernel — best effort


def null_backend() -> _Backend:
    """No-op backend: limits are bookkeeping only (hollow-node scale tests)."""
    return _Backend()


def detect_backend(node_name: str, cgroup_root: str = "/sys/fs/cgroup") -> _Backend:
    """Pick the strongest *proven* flavor: unified v2 whose delegation
    actually surfaces memory.max in a probe child > hybrid v1 with a
    writable memory hierarchy > null."""
    sub = os.path.join("ktpu", node_name)
    ctrl_file = os.path.join(cgroup_root, "cgroup.controllers")
    if os.path.exists(ctrl_file):
        try:
            controllers = open(ctrl_file).read().split()
            if "memory" in controllers and _v2_delegation_works(cgroup_root):
                return _V2Backend(os.path.join(cgroup_root, sub), cgroup_root)
        except OSError:
            pass
    # hybrid: v1 memory hierarchy writable
    mem_root = os.path.join(cgroup_root, "memory")
    cpu_root = os.path.join(cgroup_root, "cpu")
    cpuacct_root = os.path.join(cgroup_root, "cpuacct")
    if os.path.isdir(mem_root) and _writable(mem_root):
        # cpuacct co-mounted with cpu ("cpu,cpuacct") or its own mount?
        separate_acct = (
            os.path.isdir(cpuacct_root)
            and not os.path.exists(os.path.join(cpu_root, "cpuacct.usage"))
        )
        return _V1Backend(
            os.path.join(mem_root, sub),
            os.path.join(cpu_root, sub),
            os.path.join(cpuacct_root, sub) if separate_acct else "",
        )
    return null_backend()


def _v2_delegation_works(cgroup_root: str) -> bool:
    """Enabling +memory in root subtree_control must make memory.max appear
    in a probe child — claiming enforcement that silently isn't real is
    worse than none."""
    probe = os.path.join(cgroup_root, f"ktpu-probe-{os.getpid()}")
    try:
        os.mkdir(probe)
    except OSError:
        return False
    try:
        _write(os.path.join(cgroup_root, "cgroup.subtree_control"), "+memory +cpu")
        return os.path.exists(os.path.join(probe, "memory.max"))
    finally:
        try:
            os.rmdir(probe)
        except OSError:
            pass


def _writable(root: str) -> bool:
    probe = os.path.join(root, f".ktpu-probe-{os.getpid()}")
    try:
        os.mkdir(probe)
        os.rmdir(probe)
        return True
    except OSError:
        return False


class ContainerManager:
    """Owns the node's cgroup tree (ref container_manager_linux.go:619).

    The kubelet calls `ensure_pod_cgroup` before starting containers and
    hands the returned join files to the runtime; `pod_stats` feeds the
    stats pipeline with cgroup ground truth; `node_allocatable` reserves
    system overhead out of capacity."""

    QOS_DIRS = {QOS_GUARANTEED: "guaranteed", QOS_BURSTABLE: "burstable",
                QOS_BESTEFFORT: "besteffort"}

    def __init__(self, node_name: str, cgroup_root: str = "/sys/fs/cgroup",
                 system_reserved: Optional[Dict[str, str]] = None,
                 backend: Optional[_Backend] = None, enforce: bool = True):
        self.node_name = node_name
        if backend is not None:
            self.backend = backend
        elif enforce:
            self.backend = detect_backend(node_name, cgroup_root)
        else:
            self.backend = null_backend()
        self.system_reserved = system_reserved or {}
        self._lock = locksan.make_lock("ContainerManager._lock")
        self._pod_rel: Dict[str, str] = {}  # uid -> qos/pod<uid>
        self._cpu_samples: Dict[str, Tuple[float, float]] = {}
        if self.backend.name != "null":
            for qos_dir in self.QOS_DIRS.values():
                self.backend.ensure(qos_dir)

    @property
    def enforcing(self) -> bool:
        return self.backend.name != "null"

    # -------------------------------------------------------- pod lifecycle

    def ensure_pod_cgroup(self, pod: t.Pod):
        """Create the pod cgroup under its QoS parent and apply the summed
        container limits (ref qos_container_manager: pod-level enforcement,
        containers nested under it)."""
        if not self.enforcing:
            return
        uid = pod.metadata.uid
        with self._lock:
            if uid in self._pod_rel:
                return  # already ensured this kubelet incarnation
        rel = f"{self.QOS_DIRS[qos_class(pod)]}/pod{uid}"
        self.backend.ensure(rel)
        cpu_milli, mem_bytes = pod_resource_totals(pod)
        self.backend.set_limits(rel, cpu_milli, mem_bytes)
        with self._lock:
            self._pod_rel[uid] = rel

    def container_join_files(self, pod: t.Pod, container: t.Container) -> List[str]:
        """Per-container child cgroup under the pod's (inherits the pod
        limits; container-level limits applied when set); returns the
        cgroup.procs files the starting process writes itself into."""
        if not self.enforcing:
            return []
        self.ensure_pod_cgroup(pod)
        uid = pod.metadata.uid
        with self._lock:
            pod_rel = self._pod_rel[uid]
        rel = f"{pod_rel}/{container.name}"
        self.backend.ensure(rel)
        lim = container.resources.limits or {}
        self.backend.set_limits(
            rel,
            parse_milli(lim["cpu"]) if "cpu" in lim else None,
            int(parse_quantity(lim["memory"])) if "memory" in lim else None,
        )
        if isinstance(self.backend, _V1Backend):
            return self.backend.procs_files(rel)
        pf = self.backend.procs_file(rel)
        return [pf] if pf else []

    def remove_pod_cgroup(self, uid: str):
        with self._lock:
            rel = self._pod_rel.pop(uid, None)
            for k in [k for k in self._cpu_samples if k[0] == uid]:
                self._cpu_samples.pop(k, None)
        if rel:
            # children first (rmdir requires empty dirs); ignore busy dirs —
            # a re-sync retries after the processes die
            for sub in self._list_children(rel):
                self.backend.remove(f"{rel}/{sub}")
            self.backend.remove(rel)

    def _list_children(self, rel: str) -> List[str]:
        roots = []
        if isinstance(self.backend, _V1Backend):
            roots = self.backend._paths(rel)
        elif isinstance(self.backend, _V2Backend):
            roots = [self.backend._p(rel)]
        out = set()
        for root in roots:
            try:
                out.update(d for d in os.listdir(root)
                           if os.path.isdir(os.path.join(root, d)))
            except OSError:
                pass
        return sorted(out)

    def oom_kill_count(self, uid: str) -> int:
        """Cumulative kernel OOM kills charged to this pod's cgroup subtree.
        Callers diff against a baseline — the counter never resets, so a
        single historic OOM must not label every later SIGKILL."""
        with self._lock:
            rel = self._pod_rel.get(uid)
        return self.backend.oom_kill_count(rel) if rel else 0

    # -------------------------------------------------------------- stats

    def _rated_stats(self, key: tuple, rel: str) -> Optional[Dict[str, float]]:
        raw = self.backend.stats(rel)
        if raw is None:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._cpu_samples.get(key)
            self._cpu_samples[key] = (raw["cpu_ns_total"], now)
        cpu = 0.0
        if last is not None and now > last[1]:
            cpu = max(0.0, (raw["cpu_ns_total"] - last[0]) / 1e9 / (now - last[1]))
        return {"cpu": cpu, "memory": raw["memory"]}

    def pod_stats(self, uid: str) -> Optional[Dict[str, float]]:
        """{"cpu": cores, "memory": bytes} from the pod cgroup (hierarchical
        — includes every process of every container); cpu is a rate from
        cumulative-usage deltas between calls (cadvisor's method)."""
        with self._lock:
            rel = self._pod_rel.get(uid)
        if rel is None:
            return None
        return self._rated_stats((uid, ""), rel)

    def container_stats(self, uid: str, container_name: str) -> Optional[Dict[str, float]]:
        """Cgroup ground truth for one container (its child cgroup)."""
        with self._lock:
            rel = self._pod_rel.get(uid)
        if rel is None:
            return None
        return self._rated_stats((uid, container_name), f"{rel}/{container_name}")

    def cleanup(self):
        """Best-effort teardown of this node's whole cgroup subtree (kubelet
        stop); cgroups with live processes survive and are re-adopted."""
        if not self.enforcing:
            return
        with self._lock:
            uids = list(self._pod_rel)
        for uid in uids:
            self.remove_pod_cgroup(uid)
        for qos_dir in self.QOS_DIRS.values():
            self.backend.remove(qos_dir)
        self.backend.remove("")

    # -------------------------------------------------- node allocatable

    def node_allocatable(self, capacity: Dict[str, str]) -> Dict[str, str]:
        """allocatable = capacity - system reserved (ref:
        node_container_manager.go; scheduling works against this)."""
        out = dict(capacity)
        for res, reserved in self.system_reserved.items():
            if res not in capacity:
                continue
            if res == "cpu":
                left = parse_milli(capacity[res]) - parse_milli(reserved)
                out[res] = f"{max(0, left)}m"
            else:
                left = parse_quantity(capacity[res]) - parse_quantity(reserved)
                out[res] = str(int(max(0, left)))
        return out
