"""CPU manager: exclusive core pinning for Guaranteed integer-CPU pods.

Ref: pkg/kubelet/cm/cpumanager/cpu_manager.go (policies none/static),
cm/cpumanager/topology/topology.go (socket/core/thread discovery from
cadvisor), cm/cpumanager/state/state_file.go:45-119 (JSON checkpoint of
assignments + default pool), cm/cpumanager/cpu_assignment.go
(takeByTopology: whole sockets, then whole physical cores, then threads).

TPU-native twist: the reference writes cpuset cgroups; here containers are
ProcessRuntime host processes, so pinning rides the same pre-exec channel
as cgroup joining — the child applies its cpuset with sched_setaffinity
(taskset preamble) before exec, and every grandchild (the JAX runtime's
worker threads) inherits it.  Exclusive cores matter on TPU hosts: the
host's feeding threads (infeed, dispatch) stall the chip when they migrate
or share a hyperthread with noisy neighbors.

State is checkpointed to <root>/cpu_manager_state.json exactly so a kubelet
restart neither double-assigns a core nor leaks one (mirrors
state_file.go's {policyName, defaultCpuSet, entries} schema).
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..api import types as t
from ..utils import faultline, locksan
from ..utils.quantity import parse_quantity
from .eviction import QOS_GUARANTEED, qos_class

POLICY_NONE = "none"
POLICY_STATIC = "static"


class CPUExhaustedError(Exception):
    """Exclusive-cpu pool can't cover a Guaranteed integer-cpu request
    (ref policy_static.go Allocate error path)."""


# ------------------------------------------------------------------ topology

@dataclass(frozen=True)
class CPUInfo:
    cpu: int        # logical cpu id
    core: int       # physical core id (global: socket<<16 | core_id)
    socket: int


@dataclass
class CPUTopology:
    """Logical-cpu -> (physical core, socket) map (ref topology.go)."""

    cpus: List[CPUInfo] = field(default_factory=list)

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    def cpus_per_core(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        for c in self.cpus:
            out.setdefault(c.core, []).append(c.cpu)
        return out

    def cpus_per_socket(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        for c in self.cpus:
            out.setdefault(c.socket, []).append(c.cpu)
        return out

    @staticmethod
    def discover(sysfs: str = "/sys/devices/system/cpu") -> "CPUTopology":
        """Read core/package ids from sysfs; flat fallback when absent."""
        cpus: List[CPUInfo] = []
        try:
            entries = sorted(
                int(d[3:]) for d in os.listdir(sysfs)
                if d.startswith("cpu") and d[3:].isdigit()
            )
        except OSError:
            entries = []
        for cpu in entries:
            topo = os.path.join(sysfs, f"cpu{cpu}", "topology")
            try:
                core = int(open(os.path.join(topo, "core_id")).read())
                socket = int(open(os.path.join(topo, "physical_package_id")).read())
            except OSError:
                core, socket = cpu, 0
            cpus.append(CPUInfo(cpu=cpu, core=(socket << 16) | core, socket=socket))
        if not cpus:
            n = os.cpu_count() or 1
            cpus = [CPUInfo(cpu=i, core=i, socket=0) for i in range(n)]
        return CPUTopology(cpus=cpus)

    @staticmethod
    def synthetic(sockets: int, cores_per_socket: int,
                  threads_per_core: int) -> "CPUTopology":
        """Deterministic topology for tests (cpu ids socket-major)."""
        cpus = []
        cpu = 0
        for s in range(sockets):
            for c in range(cores_per_socket):
                for _ in range(threads_per_core):
                    cpus.append(CPUInfo(cpu=cpu, core=(s << 16) | c, socket=s))
                    cpu += 1
        return CPUTopology(cpus=cpus)


def take_by_topology(topo: CPUTopology, available: Set[int], want: int) -> Set[int]:
    """Pick `want` cpus preferring whole sockets, then whole physical cores,
    then leftover threads (ref cpu_assignment.go takeByTopology). Raises
    ValueError when not enough cpus are free."""
    if want > len(available):
        raise ValueError(f"want {want} cpus, only {len(available)} available")
    picked: Set[int] = set()

    def free_in(group: List[int]) -> List[int]:
        return [c for c in group if c in available and c not in picked]

    # whole sockets first
    for _, group in sorted(topo.cpus_per_socket().items()):
        free = free_in(group)
        if len(free) == len(group) and len(free) <= want - len(picked):
            picked.update(free)
    # whole physical cores next
    if len(picked) < want:
        for _, group in sorted(topo.cpus_per_core().items()):
            free = free_in(group)
            if free and len(free) == len(group) and len(free) <= want - len(picked):
                picked.update(free)
    # single threads last; prefer threads on partially-used cores so intact
    # cores stay intact for the next exclusive pod
    if len(picked) < want:
        partial: List[int] = []
        intact: List[int] = []
        for _, group in sorted(topo.cpus_per_core().items()):
            free = free_in(group)
            (partial if len(free) < len(group) else intact).extend(free)
        for c in partial + intact:
            if len(picked) == want:
                break
            picked.add(c)
    return picked


# -------------------------------------------------------------------- state

class CPUManagerState:
    """Checkpointed assignment state (ref state_file.go:45-119)."""

    def __init__(self, path: str = ""):
        self.path = path
        self.policy = POLICY_STATIC
        self.default_cpuset: Set[int] = set()
        # "uid/container" -> set of cpus
        self.entries: Dict[str, Set[int]] = {}

    def load(self) -> bool:
        if not self.path or not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                raw = json.load(f)
            self.policy = raw.get("policyName", POLICY_STATIC)
            self.default_cpuset = set(raw.get("defaultCpuSet", []))
            self.entries = {k: set(v) for k, v in raw.get("entries", {}).items()}
            return True
        except (OSError, ValueError):
            return False

    def save(self):
        if not self.path:
            return
        faultline.check("kubelet.statefile")  # checkpoint write boundary
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "policyName": self.policy,
                "defaultCpuSet": sorted(self.default_cpuset),
                "entries": {k: sorted(v) for k, v in self.entries.items()},
            }, f)
        os.replace(tmp, self.path)


# ------------------------------------------------------------------ manager

def _exclusive_cpus_wanted(pod: t.Pod, container: t.Container) -> int:
    """Static policy admits a container to the exclusive pool only when the
    pod is Guaranteed and this container asks for a whole number of cpus
    (ref policy_static.go guaranteedCPUs)."""
    if qos_class(pod) != QOS_GUARANTEED:
        return 0
    lim = (container.resources.limits or {}).get("cpu")
    if lim is None:
        return 0
    q = parse_quantity(lim)
    if q != int(q) or int(q) == 0:
        return 0
    return int(q)


class CPUManager:
    """Static-policy CPU manager. The kubelet asks `cpuset_for_container`
    while building the ContainerConfig; non-exclusive containers get the
    shared (default) pool so they can never run on an exclusively-assigned
    core."""

    def __init__(self, policy: str = POLICY_NONE,
                 topology: Optional[CPUTopology] = None,
                 state_path: str = "",
                 reserved_cpus: Optional[int] = None):
        self.policy = policy
        self._lock = locksan.make_lock("CPUManager._lock")
        # called (with no args, outside the lock) whenever the shared pool
        # changes — the kubelet re-pins running shared containers so they
        # never keep running on a newly-exclusive core
        self.on_pool_change = None
        if policy != POLICY_STATIC:
            # disabled: no sysfs scan, no checkpoint I/O — hollow-node scale
            # tests construct hundreds of kubelets with the policy off
            self.topology = topology or CPUTopology(cpus=[])
            self.state = CPUManagerState("")
            self._reserved = set()
            return
        self.topology = topology or CPUTopology.discover()
        self.state = CPUManagerState(state_path)
        all_cpus = {c.cpu for c in self.topology.cpus}
        # reserved cpus stay in the shared pool permanently (system overhead,
        # ref policy_static.go reserved); lowest-numbered cpus by convention.
        # The static policy REQUIRES a nonzero reserve upstream (the kubelet
        # refuses to start otherwise) — default to one cpu so the shared pool
        # can never drain to nothing and void exclusivity for everyone.
        if reserved_cpus is None:
            reserved_cpus = 1
        self._reserved = set(sorted(all_cpus)[:reserved_cpus])
        if not self.state.load():
            self.state.default_cpuset = set(all_cpus)
        else:
            # drop stale cpus (topology changed across restart), re-add any
            # cpu that vanished from both pools
            known = set(all_cpus)
            self.state.default_cpuset &= known
            assigned = set()
            for k in list(self.state.entries):
                self.state.entries[k] &= known
                # a checkpoint written under a different reserve may have
                # handed a now-reserved cpu out exclusively; reclaim it so
                # the reserved-fallback pool never overlaps an exclusive
                # assignment (the repin callback re-pins live containers)
                if self.state.entries[k] & self._reserved:
                    # a now-reserved cpu was in the exclusive set: drop the
                    # whole entry so the container is REALLOCATED at full
                    # size on its next lookup — shrinking it in place would
                    # silently under-deliver the cpus it was promised
                    del self.state.entries[k]
                    continue
                assigned |= self.state.entries[k]
            missing = known - self.state.default_cpuset - assigned
            self.state.default_cpuset |= missing
        self.state.policy = policy
        self.state.save()

    @property
    def enabled(self) -> bool:
        return self.policy == POLICY_STATIC and self.topology.num_cpus > 1

    # ------------------------------------------------------------ assignment

    def cpuset_for_container(self, pod: t.Pod, container: t.Container) -> Optional[Set[int]]:
        """Exclusive cpus for a Guaranteed integer-cpu container, the shared
        pool for everything else, None when the policy is off (no pinning)."""
        if not self.enabled:
            return None
        uid = pod.metadata.uid
        key = f"{uid}/{container.name}"
        want = _exclusive_cpus_wanted(pod, container)
        with self._lock:
            if key in self.state.entries:
                return set(self.state.entries[key])
            if want <= 0:
                return self._shared_pool_locked()
            allocatable = self.state.default_cpuset - self._reserved
            try:
                picked = take_by_topology(self.topology, allocatable, want)
            except ValueError:
                # not enough exclusive cpus: fail the container start (ref
                # policy_static.go Allocate returns an error) — a silent
                # shared-pool fallback would void the exclusivity other
                # Guaranteed containers were promised.  The kubelet turns
                # this into FailedStart + backoff, so freed cpus are retried.
                raise CPUExhaustedError(
                    f"not enough exclusive cpus for {key}: want {want}, "
                    f"allocatable {len(allocatable)}")
            self.state.entries[key] = picked
            self.state.default_cpuset -= picked
            self.state.save()
        self._notify_pool_change()
        return set(picked)

    def _shared_pool_locked(self) -> Optional[Set[int]]:
        """The pool a non-exclusive container runs on.  When every cpu is
        exclusively assigned, shared containers fall back to the reserved
        cpus — an empty cpuset would mean 'no pinning at all', i.e. free
        run of the exclusive cores."""
        if self.state.default_cpuset:
            return set(self.state.default_cpuset)
        if self._reserved:
            return set(self._reserved)
        return None

    def shared_pool(self) -> Optional[Set[int]]:
        with self._lock:
            return self._shared_pool_locked()

    def _notify_pool_change(self):
        cb = self.on_pool_change
        if cb is not None:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 — repinning is best-effort
                print(f"cpumanager: pool-change callback failed: {e}",
                      file=sys.stderr)

    def release_pod(self, uid: str):
        """Return the pod's exclusive cpus to the shared pool (pod deleted
        or terminal — ref removeStaleState)."""
        if not self.enabled:
            return
        with self._lock:
            changed = False
            for key in [k for k in self.state.entries if k.startswith(f"{uid}/")]:
                self.state.default_cpuset |= self.state.entries.pop(key)
                changed = True
            if changed:
                self.state.save()
        if changed:
            self._notify_pool_change()

    def reconcile(self, live_uids: Set[str]):
        """Drop assignments whose pod no longer exists (kubelet restart sync:
        state file may know pods the apiserver has deleted)."""
        if not self.enabled:
            return
        with self._lock:
            stale = {k.split("/", 1)[0] for k in self.state.entries} - set(live_uids)
        for uid in stale:
            self.release_pod(uid)

    def assigned_cpus(self) -> Dict[str, Set[int]]:
        with self._lock:
            return {k: set(v) for k, v in self.state.entries.items()}
