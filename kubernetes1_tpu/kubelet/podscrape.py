"""Pod /metrics scrape agent: the kubelet half of the custom-metrics plane.

The kubelet already publishes CPU/memory PodMetrics (`_publish_metrics`,
the resource-metrics hop).  Workload SLIs — QPS, in-flight requests,
latency histograms — live on the POD's own /metrics endpoint
(obs/appmetrics), declared via the ``obs.ktpu.io/scrape-port``/
``scrape-path`` annotations.  The PodScraper lifts them into
PodCustomMetrics objects, the ``custom.metrics.k8s.io`` pipeline's
storage, which the HPA's Pods-type metric specs consume.

Contract (the PR 11 collector rule, node-local edition):

- ``reconcile(pods)`` is called from the kubelet's existing stats loop
  and only DIFFS the annotated-pod set against the running scrape
  targets — O(annotated pods), no I/O, so 30k hollow pods without
  annotations cost the sync loop nothing;
- each annotated pod is a TIMER on the shared event loop
  (utils/eventloop) whose tick submits the blocking fetch to the
  bounded shared worker pool, re-arming only after it completes —
  same per-target isolation as the old thread-per-pod model (the
  ``obs.pod_scrape`` faultline site still wraps the fetch; a dead or
  slow pod endpoint wedges one pool slot, never the kubelet sync loop
  or a sibling's scrape) at a bounded thread count;
- a failing scrape keeps the LAST-GOOD samples and republishes them with
  ``stale=True`` (consumers must treat stale as missing — the HPA holds
  its last decision instead of flapping to zero);
- counter samples additionally publish a scrape-derived ``<name>:rate``
  (events/second between the last two good scrapes) so autoscalers can
  target request RATES without every workload exporting its own gauge
  (the prometheus-adapter ``rate()`` analog).
"""

from __future__ import annotations

import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..client import retry as _retry
from ..machinery import ApiError, NotFound, now_iso
from ..obs import aggregate
from ..obs.appmetrics import sample_value, scrape_target  # noqa: F401 — sample_value re-exported: the value-of-metric-on-pod definition lives with the scrape contract
from ..utils import eventloop, faultline, locksan
from ..utils.logutil import RateLimitedReporter

# Sample-count cap per pod: a misbehaving workload dumping thousands of
# series must not turn every scrape into a megabyte PodCustomMetrics
# write.  64 named series is far past any sane SLI surface.
MAX_SAMPLES = 64


class _Target:
    """One annotated pod's scrape state.  Mutated by its scrape jobs
    (shared worker pool); read by reconcile/render under the scraper
    lock."""

    def __init__(self, key: str, uid: str, url: str, pod: t.Pod):
        self.key = key
        self.uid = uid
        self.url = url
        self.namespace = pod.metadata.namespace
        self.pod_name = pod.metadata.name
        self.labels = dict(pod.metadata.labels or {})
        self.stop = threading.Event()
        self.gone = False  # pod vanished (vs replaced): object is garbage
        self.adopt_checked = False  # pre-restart object looked for once
        self.timer: Optional[eventloop.Timer] = None  # next interval tick
        # scrape state (last-good snapshot semantics)
        self.samples: List[t.MetricSample] = []
        self.stale = False
        self.published_stale = True  # nothing published yet
        self.last_ok_mono: Optional[float] = None
        self.last_counters: Dict[str, float] = {}
        self.scrapes = 0
        self.errors = 0
        self.last_duration_s = 0.0
        self.rv: Optional[str] = None  # published object's rv cache


def _extract_samples(parsed: aggregate.ParsedMetrics,
                     prev_counters: Dict[str, float],
                     dt: Optional[float],
                     ) -> Tuple[List[t.MetricSample], Dict[str, float]]:
    """ParsedMetrics -> (MetricSample list, counter snapshot for the next
    rate derivation).  Histogram internals (``_bucket`` series, quantile
    children) are skipped — the HPA consumes scalars; the full histogram
    stays on the pod endpoint for humans and the fleet merge."""
    samples: List[t.MetricSample] = []
    counters: Dict[str, float] = {}
    for key, value in parsed.samples.items():
        try:
            name, labels = aggregate.parse_series_key(key)
        except ValueError:
            continue
        if "quantile" in labels or "le" in labels \
                or name.endswith("_bucket"):
            continue
        fam_type = parsed.types.get(name, "")
        if not fam_type:
            for suffix in ("_sum", "_count"):
                if name.endswith(suffix) \
                        and name[: -len(suffix)] in parsed.types:
                    fam_type = "counter"  # histogram internals: cumulative
        is_counter = fam_type == "counter" or name.endswith("_total")
        if len(samples) < MAX_SAMPLES:
            samples.append(t.MetricSample(
                name=name, value=value,
                type="counter" if is_counter else (fam_type or "gauge"),
                labels=labels))
        if is_counter:
            counters[key] = value
            if dt and dt > 0 and key in prev_counters \
                    and len(samples) < MAX_SAMPLES:
                delta = value - prev_counters[key]
                if delta >= 0:  # a restarted workload resets its counters
                    samples.append(t.MetricSample(
                        name=f"{name}:rate", value=delta / dt,
                        type="rate", labels=labels))
    return samples, counters


class PodScraper:
    """See module docstring.  Owned by a Kubelet; `reconcile` is wired
    into the kubelet's stats loop, `render_metrics` into the kubelet
    server's /metrics."""

    def __init__(self, clientset, node_name: str, interval: float = 1.0,
                 fetch_timeout: float = 1.0):
        self.cs = clientset
        self.node_name = node_name
        self.interval = interval
        self.fetch_timeout = fetch_timeout
        self._targets: Dict[str, _Target] = {}
        self._lock = locksan.make_lock("podscrape.PodScraper._lock")
        self._loop = eventloop.shared_loop()
        self._pool = eventloop.shared_pool()
        self._stopping = threading.Event()
        self._err_reporter = RateLimitedReporter(
            f"podscrape/{node_name}", window=30.0)
        self.scrapes_total = 0
        self.errors_total = 0
        self.publish_errors_total = 0

    # ----------------------------------------------------------- reconcile

    def reconcile(self, pods: List[t.Pod]):
        """Diff the annotated-pod set against running scrape targets.
        Called from the kubelet stats loop — never blocks on a scrape."""
        want: Dict[str, Tuple[str, str, t.Pod]] = {}
        for pod in pods:
            if pod.metadata.deletion_timestamp:
                continue
            url = scrape_target(pod)
            if url is not None:
                want[pod.key()] = (pod.metadata.uid, url, pod)
        to_start: List[_Target] = []
        to_gc: List[_Target] = []
        with self._lock:
            for key, tgt in list(self._targets.items()):
                cur = want.get(key)
                if cur is None or cur[0] != tgt.uid or cur[1] != tgt.url:
                    # gone, replaced (new uid = new pod instance), or
                    # re-annotated: the old target dies, state resets
                    del self._targets[key]
                    if cur is None:
                        tgt.gone = True  # before stop.set: see _publish
                        to_gc.append(tgt)
                    tgt.stop.set()
                    if tgt.timer is not None:
                        tgt.timer.cancel()
                elif dict(cur[2].metadata.labels or {}) != tgt.labels:
                    # relabeled in place: the published object's labels
                    # must follow (labelSelector reads select over them)
                    tgt.labels = dict(cur[2].metadata.labels or {})
            for key, (uid, url, pod) in want.items():
                if key not in self._targets:
                    tgt = self._targets[key] = _Target(key, uid, url, pod)
                    to_start.append(tgt)
        for tgt in to_start:
            self._schedule_scrape(tgt)
        for tgt in to_gc:
            self._gc_object(tgt)

    def _schedule_scrape(self, tgt: _Target):
        """Submit one scrape of ``tgt`` to the shared pool; the job
        re-arms the target's interval timer AFTER it completes — at most
        one scrape per target queued or running, the old per-pod
        thread's ``scrape_once(); wait(interval)`` pacing."""
        def job():
            if tgt.stop.is_set() or self._stopping.is_set():
                return
            self.scrape_once(tgt)
            if tgt.stop.is_set() or self._stopping.is_set():
                return
            tgt.timer = self._loop.call_later(
                self.interval, lambda: self._pool.submit(job))

        self._pool.submit(job)

    def _gc_object(self, tgt: _Target):
        """Best-effort delete of a vanished pod's PodCustomMetrics — a
        stale object for a dead pod would read as a live (stale) signal."""
        try:
            self.cs.podcustommetrics.delete(tgt.pod_name, tgt.namespace)
        except (ApiError, ConnectionError, TimeoutError, OSError):
            pass  # object may never have been published; next pod wins it

    # ------------------------------------------------------------- scraping

    def _fetch(self, url: str) -> str:
        """One GET behind the obs.pod_scrape faultline site (an injected
        drop/delay/error lands HERE, inside the pod's own thread)."""
        faultline.check("obs.pod_scrape")
        with urllib.request.urlopen(url, timeout=self.fetch_timeout) as r:
            return r.read().decode()

    def scrape_once(self, tgt: _Target) -> bool:
        t0 = time.monotonic()
        try:
            text = _retry.call_with_retries(
                lambda: self._fetch(tgt.url), steps=2,
                reason="pod_scrape",
                backoff=_retry.Backoff(base=0.02, cap=0.1))
        except Exception as e:  # noqa: BLE001 — a dead pod endpoint is a data point
            with self._lock:
                tgt.errors += 1
                self.errors_total += 1
                tgt.stale = True
            self._err_reporter.report(f"scrape {tgt.key}: {e}")
            if tgt.last_ok_mono is not None:
                # fresh -> stale transition: republish the last-good
                # samples MARKED stale — consumers hold, not flap.
                # _publish dedups on published_stale, so the mark lands
                # exactly once per transition but a FAILED mark write is
                # retried on every later failing scrape until it sticks
                # (else consumers read stale data as fresh all outage).
                self._publish(tgt)
            elif not tgt.adopt_checked:
                # never scraped OK in THIS process but a pre-restart
                # kubelet may have published a fresh-looking object for
                # this pod — find it and stale-mark it, or consumers
                # treat a dead endpoint's last samples as live truth
                # for the whole outage
                self._adopt_stale(tgt)
            return False
        parsed = aggregate.parse_metrics_text(text)
        now = time.monotonic()
        dt = (now - tgt.last_ok_mono) if tgt.last_ok_mono is not None \
            else None
        samples, counters = _extract_samples(
            parsed, tgt.last_counters, dt)
        with self._lock:
            tgt.samples = samples
            tgt.last_counters = counters
            tgt.last_ok_mono = now
            tgt.stale = False
            tgt.last_duration_s = now - t0
            tgt.scrapes += 1
            self.scrapes_total += 1
        self._publish(tgt)
        return True

    def _adopt_stale(self, tgt: _Target):
        """First-failure path of a target that has never scraped OK in
        this process (kubelet restart mid-outage): adopt any published
        PodCustomMetrics for the pod as last-good and stale-mark it.
        Transport errors retry on the next failing scrape; NotFound
        settles the question for good."""
        try:
            cur = self.cs.podcustommetrics.get(tgt.pod_name, tgt.namespace)
        except NotFound:
            with self._lock:
                tgt.adopt_checked = True  # nothing published: new pod
            return
        except (ApiError, ConnectionError, TimeoutError, OSError):
            return  # can't tell yet — re-check on the next failure
        with self._lock:
            tgt.adopt_checked = True
            tgt.rv = cur.metadata.resource_version
            tgt.samples = list(cur.samples)  # the outage's last-good
            if cur.stale:
                tgt.published_stale = True  # already marked: done
                return
            tgt.published_stale = False
        self._publish(tgt)  # tgt.stale is set by our caller

    # ------------------------------------------------------------ publishing

    def _publish(self, tgt: _Target):
        """Upsert the PodCustomMetrics object (steady state is update —
        the `_upsert_metrics` shape, but per-target rv state so N pod
        threads never share a cache slot)."""
        if tgt.stop.is_set():
            return  # target retired mid-scrape: don't resurrect a GC'd object
        with self._lock:
            obj = t.PodCustomMetrics(
                timestamp=now_iso(), stale=tgt.stale,
                samples=list(tgt.samples))
            obj.metadata.name = tgt.pod_name
            obj.metadata.namespace = tgt.namespace
            obj.metadata.labels = dict(tgt.labels)
            rv = tgt.rv
            already_published_stale = tgt.published_stale and tgt.stale
        if already_published_stale:
            return  # stale republish happens once per transition
        client = self.cs.podcustommetrics
        try:
            if rv is not None:
                obj.metadata.resource_version = rv
                try:
                    updated = client.update(obj)
                except NotFound:
                    obj.metadata.resource_version = ""
                    updated = client.create(obj, tgt.namespace)
            else:
                try:
                    updated = client.create(obj, tgt.namespace)
                except ApiError:
                    # AlreadyExists (a restarted kubelet, or the prior
                    # pod of a reused name): adopt the live object's rv
                    cur = client.get(tgt.pod_name, tgt.namespace)
                    obj.metadata.resource_version = \
                        cur.metadata.resource_version
                    updated = client.update(obj)
        except ApiError:  # Conflict: refresh the rv, next cycle wins
            with self._lock:
                tgt.rv = None
                self.publish_errors_total += 1
            return
        except (ConnectionError, TimeoutError, OSError) as e:
            with self._lock:
                self.publish_errors_total += 1
            self._err_reporter.report(f"publish {tgt.key}: {e}")
            return
        with self._lock:
            tgt.rv = updated.metadata.resource_version
            tgt.published_stale = obj.stale
        if tgt.stop.is_set() and tgt.gone:
            # pod vanished while the write was in flight: reconcile's GC
            # delete may have run BEFORE our update/create landed (the
            # NotFound->create fallback resurrects it), and no later
            # pass would ever clean the orphan.  reconcile sets gone,
            # then stop, then deletes; we re-check after writing — one
            # of the two deletes always sees the object last.  A
            # replaced (uid/url change) target keeps the object: its
            # successor thread owns it now.
            self._gc_object(tgt)

    # ------------------------------------------------------------ reporting

    def render_metrics(self) -> str:
        """Scrape-health lines for the kubelet's /metrics — the per-node
        half the ObsCollector federates into the fleet scaling view."""
        now = time.monotonic()
        with self._lock:
            tgts = sorted(self._targets.values(), key=lambda x: x.key)
            lines = [
                "# TYPE ktpu_podscrape_targets gauge",
                f"ktpu_podscrape_targets {len(tgts)}",
                "# TYPE ktpu_podscrape_scrapes_total counter",
                f"ktpu_podscrape_scrapes_total {self.scrapes_total}",
                "# TYPE ktpu_podscrape_errors_total counter",
                f"ktpu_podscrape_errors_total {self.errors_total}",
                "# TYPE ktpu_podscrape_publish_errors_total counter",
                f"ktpu_podscrape_publish_errors_total "
                f"{self.publish_errors_total}",
            ]
            if tgts:
                lines.append("# TYPE ktpu_podscrape_up gauge")
                for tg in tgts:
                    lines.append(
                        f'ktpu_podscrape_up{{pod="{tg.key}"}} '
                        f"{0 if tg.stale or tg.last_ok_mono is None else 1}")
                lines.append(
                    "# TYPE ktpu_podscrape_staleness_seconds gauge")
                for tg in tgts:
                    stale_s = (now - tg.last_ok_mono
                               if tg.last_ok_mono is not None else -1.0)
                    lines.append(
                        f'ktpu_podscrape_staleness_seconds'
                        f'{{pod="{tg.key}"}} {stale_s:.3f}')
        return "\n".join(lines) + "\n"

    def targets(self) -> List[_Target]:
        with self._lock:
            return list(self._targets.values())

    def stop(self):
        self._stopping.set()
        with self._lock:
            tgts = list(self._targets.values())
            self._targets.clear()
        for tgt in tgts:
            tgt.stop.set()
            if tgt.timer is not None:
                # in-flight pool jobs check the stop flags before they
                # scrape and never re-arm past them — nothing to join
                tgt.timer.cancel()
