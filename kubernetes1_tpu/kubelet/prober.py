"""Probe manager: per-container liveness/readiness workers.

Ref: pkg/kubelet/prober/{prober_manager.go,worker.go,prober.go} — one worker
per (container, probe type) running on the probe's period; readiness results
gate the pod Ready condition (and through it Endpoints membership); a
liveness failure past failureThreshold makes the kubelet restart the
container. Probe actions: exec (run in container), httpGet, tcpSocket.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from ..api import types as t
from ..utils import faultline, locksan

SUCCESS = "success"
FAILURE = "failure"
UNKNOWN = "unknown"


def run_probe(probe: t.Probe, target_host: str, exec_fn=None) -> bool:
    """Execute one probe attempt. exec_fn(command) -> exit code (for exec
    probes; the runtime provides the in-container execution)."""
    try:
        # seeded chaos can fail any probe attempt (kubelet.probe site):
        # restart/readiness churn from flaky probes is a failure mode the
        # eviction and endpoints paths must absorb
        faultline.check("kubelet.probe")
    except faultline.FaultInjected:
        return False
    if probe.exec_action is not None:
        if exec_fn is None:
            return False
        try:
            return exec_fn(probe.exec_action.command) == 0
        except Exception:  # noqa: BLE001
            return False
    if probe.http_get is not None:
        host = probe.http_get.host or target_host or "127.0.0.1"
        url = f"http://{host}:{probe.http_get.port}{probe.http_get.path}"
        try:
            with urllib.request.urlopen(url, timeout=probe.timeout_seconds) as resp:
                return 200 <= resp.status < 400
        except Exception:  # noqa: BLE001
            return False
    if probe.tcp_socket is not None:
        host = probe.tcp_socket.host or target_host or "127.0.0.1"
        try:
            with socket.create_connection(
                (host, probe.tcp_socket.port), timeout=probe.timeout_seconds
            ):
                return True
        except OSError:
            return False
    return True  # no action configured counts as success (reference behavior)


class _Worker:
    """One probe loop (ref: prober/worker.go)."""

    def __init__(self, probe: t.Probe, kind: str, target_host: str,
                 exec_fn, on_result: Callable[[str], None],
                 is_running: Optional[Callable[[], bool]] = None):
        self.probe = probe
        self.kind = kind  # "liveness" | "readiness"
        self.target_host = target_host
        self.exec_fn = exec_fn
        self.on_result = on_result
        self.is_running = is_running
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._successes = 0
        self._failures = 0

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _run(self):
        if self.probe.initial_delay_seconds:
            if self._stop.wait(self.probe.initial_delay_seconds):
                return
        # readiness starts False until the first success; liveness starts OK
        while not self._stop.is_set():
            if self.is_running is not None and not self.is_running():
                # container down (crashed / restart backoff): don't probe —
                # a failure recorded now would be charged to the NEXT
                # instance and kill it the moment it comes up (the reference
                # prober likewise only probes running containers)
                self._successes = self._failures = 0
                if self._stop.wait(max(self.probe.period_seconds, 0.05)):
                    return
                continue
            ok = run_probe(self.probe, self.target_host, self.exec_fn)
            if ok:
                self._successes += 1
                self._failures = 0
                if self._successes >= self.probe.success_threshold:
                    self.on_result(SUCCESS)
            else:
                self._failures += 1
                self._successes = 0
                if self._failures >= self.probe.failure_threshold:
                    self.on_result(FAILURE)
            if self._stop.wait(max(self.probe.period_seconds, 0.05)):
                return


class ProberManager:
    """Tracks workers per (pod_uid, container, kind) and exposes results
    (ref: prober/prober_manager.go)."""

    def __init__(self, exec_in_container=None, container_running=None):
        # exec_in_container(pod_uid, container_name, command) -> exit code
        # container_running(pod_uid, container_name) -> bool
        self.exec_in_container = exec_in_container
        self.container_running = container_running
        self._lock = locksan.make_lock("ProberManager._lock")
        self._workers: Dict[Tuple[str, str, str], _Worker] = {}
        self._results: Dict[Tuple[str, str, str], str] = {}

    def ensure_pod(self, pod: t.Pod):
        """Start workers for every probed container of a running pod."""
        uid = pod.metadata.uid
        host = pod.status.pod_ip or "127.0.0.1"
        for container in pod.spec.containers:
            for kind, probe in (
                ("liveness", container.liveness_probe),
                ("readiness", container.readiness_probe),
            ):
                if probe is None:
                    continue
                key = (uid, container.name, kind)
                with self._lock:
                    if key in self._workers:
                        continue
                    if kind == "readiness":
                        self._results[key] = UNKNOWN  # not ready until proven
                    exec_fn = None
                    cname = container.name
                    if self.exec_in_container is not None:
                        exec_fn = lambda cmd, u=uid, c=cname: self.exec_in_container(u, c, cmd)  # noqa: E731
                    is_running = None
                    if self.container_running is not None:
                        is_running = lambda u=uid, c=cname: self.container_running(u, c)  # noqa: E731
                    worker = _Worker(
                        probe, kind, host, exec_fn,
                        on_result=lambda res, k=key: self._record(k, res),
                        is_running=is_running,
                    )
                    self._workers[key] = worker
                worker.start()

    def _record(self, key, result: str):
        with self._lock:
            self._results[key] = result

    def remove_pod(self, pod_uid: str):
        with self._lock:
            for key in [k for k in self._workers if k[0] == pod_uid]:
                self._workers.pop(key).stop()
                self._results.pop(key, None)

    def restart_container(self, pod_uid: str, container_name: str):
        """Reset probe state after a container restart."""
        with self._lock:
            for kind in ("liveness", "readiness"):
                key = (pod_uid, container_name, kind)
                worker = self._workers.pop(key, None)
                if worker is not None:
                    worker.stop()
                self._results.pop(key, None)

    def is_ready(self, pod_uid: str, container_name: str) -> bool:
        """True unless a readiness probe exists and hasn't succeeded."""
        key = (pod_uid, container_name, "readiness")
        with self._lock:
            if key not in self._workers and key not in self._results:
                return True
            return self._results.get(key) == SUCCESS

    def liveness_failed(self, pod_uid: str, container_name: str) -> bool:
        key = (pod_uid, container_name, "liveness")
        with self._lock:
            return self._results.get(key) == FAILURE

    def stop(self):
        with self._lock:
            for worker in self._workers.values():
                worker.stop()
            self._workers.clear()
            self._results.clear()
