from .kubelet import Kubelet
from .runtime import FakeRuntime, ProcessRuntime, RuntimeService, ContainerConfig
from .devicemanager import DeviceManager
