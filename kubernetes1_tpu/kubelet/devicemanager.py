"""Kubelet device manager: plugin discovery, device store, pod admission,
container init — the fork's rewritten device manager, TPU-flavored.

Ref: pkg/kubelet/cm/devicemanager/{manager.go,endpoint.go,manager_store.go,
cache.go} + apis/pluginregistration/v1beta/plugin_watcher.go.  Semantics
preserved:
- socket discovery under <plugin_dir>/<domain>/<name>.sock (the PluginWatcher
  dir layout; polling stands in for fsnotify);
- per-plugin endpoint holds the connection and streams ListAndWatch device
  updates into the store; a dead endpoint marks its devices unhealthy;
- AdmitPod runs at kubelet pod admission, verifying the scheduler-assigned
  IDs against local healthy inventory and letting the plugin veto; the
  response is cached per pod uid with allocation latency recorded (the
  fork's DevicePluginAllocationLatency metric, manager.go:229-231);
- InitContainer runs before each container start and returns the injection
  spec (env/mounts/devices/annotations);
- NO local checkpoint file: assignment truth lives in
  pod.spec.extended_resources[].assigned in the API store, so kubelet
  restart-safety is free (manager.go:293-310 prunes the per-pod cache
  lazily).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, NamedTuple, Optional


from ..api import types as t
from ..deviceplugin.api import ContainerSpec, PluginClient, resource_from_socket
from ..machinery.scheme import from_dict
from ..utils import locksan
from ..utils.metrics import Histogram

class AdmitResult(NamedTuple):
    allowed: bool
    reason: str
    retriable: bool


class Endpoint:
    """One connected plugin (ref: endpoint.go)."""

    def __init__(self, manager: "DeviceManager", resource: str, socket_path: str):
        self.manager = manager
        self.resource = resource
        self.socket_path = socket_path
        self.client = PluginClient(socket_path)
        self.info: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self.info = self.client.call("GetPluginInfo")
        self._thread = threading.Thread(
            target=self._watch_loop, daemon=True, name=f"dp-{self.resource}"
        )
        self._thread.start()

    def _watch_loop(self):
        failures = 0
        while not self._stop.is_set():
            got_stream = False
            try:
                for devices in self.client.list_and_watch():
                    if self._stop.is_set():
                        return
                    got_stream = True
                    failures = 0
                    self.manager.store_update(self.resource, devices)
            except (ConnectionError, OSError):
                pass
            if self._stop.is_set():
                return
            if not os.path.exists(self.socket_path):
                # plugin gone cleanly: inventory no longer trustworthy
                self.manager.store_mark_unhealthy(self.resource)
                return
            if not got_stream:
                # socket file present but nobody answering — a killed plugin
                # leaves its socket behind; after a couple of refused
                # connects the inventory is stale
                failures += 1
                if failures == 2:
                    self.manager.store_mark_unhealthy(self.resource)
            time.sleep(0.5)  # ktpulint: ignore[KTPU013] plugin health-monitor sampling period — the two-strike unhealthy marking above counts consecutive probes at this fixed cadence; jitter would skew time-to-detection

    def admit_pod(self, pod: t.Pod, assignments: Dict[str, List[str]]) -> dict:
        return self.client.call(
            "AdmitPod",
            {
                "pod_uid": pod.metadata.uid,
                "pod_name": pod.metadata.name,
                "pod_namespace": pod.metadata.namespace,
                "assignments": assignments,
            },
        )

    def init_container(
        self, pod: t.Pod, container_name: str, device_ids: List[str]
    ) -> ContainerSpec:
        result = self.client.call(
            "InitContainer",
            {
                "pod_uid": pod.metadata.uid,
                "container_name": container_name,
                "device_ids": device_ids,
                "pod_annotations": pod.metadata.annotations,
            },
        )
        return ContainerSpec.from_dict(result or {})

    def stop(self):
        self._stop.set()
        self.client.close()


class DeviceManager:
    def __init__(self, plugin_dir: str, poll_interval: float = 0.5):
        self.plugin_dir = plugin_dir
        self.poll_interval = poll_interval
        self._lock = locksan.make_rlock("DeviceManager._lock")
        self._endpoints: Dict[str, Endpoint] = {}  # resource -> endpoint
        self._store: Dict[str, List[dict]] = {}  # resource -> device dicts
        self._admit_cache: Dict[str, dict] = {}  # pod uid -> admit result
        # device ids the PLUGIN ITSELF reported unhealthy (per resource).
        # Distinct from store_mark_unhealthy's synthetic staleness marking:
        # only an explicit ListAndWatch unhealthy report means the chip is
        # actually dead — endpoint/socket death must never kill running
        # workloads (the kubelet-restart / plugin-restart contract).
        self._reported_unhealthy: Dict[str, set] = {}
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self.allocation_latency = Histogram(
            "ktpu_device_plugin_allocation_seconds",
            "AdmitPod RPC latency (the fork's DevicePluginAllocationLatency)",
        )
        self.on_capacity_change = None  # callback for node-status push
        # callback(resource, [device ids]) fired once per plugin-reported
        # healthy->unhealthy transition: the kubelet fails running pods
        # holding those devices so their controller/gang policy reacts —
        # without it a dead chip only blocks FUTURE admits while the pod
        # that holds it spins on a bricked device forever
        self.on_device_unhealthy = None

    # ------------------------------------------------------ plugin watching

    def start(self):
        os.makedirs(self.plugin_dir, exist_ok=True)
        self._watcher = threading.Thread(target=self._watch_sockets, daemon=True)
        self._watcher.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            for ep in self._endpoints.values():
                ep.stop()
            self._endpoints.clear()

    def _scan(self) -> Dict[str, str]:
        found = {}
        try:
            for domain in os.listdir(self.plugin_dir):
                ddir = os.path.join(self.plugin_dir, domain)
                if not os.path.isdir(ddir):
                    continue
                for name in os.listdir(ddir):
                    path = os.path.join(ddir, name)
                    resource = resource_from_socket(self.plugin_dir, path)
                    if resource:
                        found[resource] = path
        except OSError:
            pass
        return found

    def _watch_sockets(self):
        while not self._stop.is_set():
            found = self._scan()
            to_start: List[tuple] = []
            with self._lock:
                for resource, path in found.items():
                    ep = self._endpoints.get(resource)
                    if ep is None or ep.socket_path != path or not ep._thread.is_alive():
                        to_start.append((resource, path, ep))
                removed = [r for r in self._endpoints if r not in found]
                for resource in removed:
                    self._endpoints.pop(resource).stop()
            for resource in removed:
                self.store_mark_unhealthy(resource)
            # Endpoint.start() does a blocking RPC — never under the manager
            # lock, or a wedged plugin freezes admission and heartbeats.
            for resource, path, old_ep in to_start:
                if old_ep is not None:
                    old_ep.stop()
                ep = Endpoint(self, resource, path)
                try:
                    ep.start()
                except (ConnectionError, OSError):
                    continue
                with self._lock:
                    cur = self._endpoints.get(resource)
                    if cur is not None and cur is not old_ep and cur._thread.is_alive():
                        ep.stop()  # raced with another registration
                    else:
                        self._endpoints[resource] = ep
            self._stop.wait(self.poll_interval)

    # ----------------------------------------------------------- the store

    def store_update(self, resource: str, devices: List[dict]):
        lost: List[str] = []
        with self._lock:
            reported = self._reported_unhealthy.setdefault(resource, set())
            for d in devices:
                if d.get("health") == t.DEVICE_HEALTHY:
                    reported.discard(d["id"])
                elif d["id"] not in reported:
                    # a NEW plugin-reported death (first frame after a
                    # kubelet restart counts too: the chip may have died
                    # while the kubelet was down)
                    reported.add(d["id"])
                    lost.append(d["id"])
            self._store[resource] = devices
        if lost and self.on_device_unhealthy:
            try:
                self.on_device_unhealthy(resource, lost)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        if self.on_capacity_change:
            try:
                self.on_capacity_change()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def store_mark_unhealthy(self, resource: str):
        """Inventory no longer trustworthy (endpoint/socket gone): blocks
        FUTURE admits only.  Deliberately does NOT fire on_device_unhealthy
        — a restarting plugin must not kill the healthy workloads it was
        serving (their truth arrives with the next ListAndWatch frame)."""
        with self._lock:
            for d in self._store.get(resource, []):
                d["health"] = t.DEVICE_UNHEALTHY
        if self.on_capacity_change:
            try:
                self.on_capacity_change()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def get_capacity(self) -> Dict[str, List[t.ExtendedResourceDevice]]:
        """ExtendedResourceMap for node status (ref: manager.go GetCapacity
        -> kubelet_node_status.go:552-621)."""
        with self._lock:
            return {
                resource: [from_dict(t.ExtendedResourceDevice, d) for d in devices]
                for resource, devices in self._store.items()
            }

    def has_plugin(self, resource: str) -> bool:
        with self._lock:
            return resource in self._endpoints

    # ------------------------------------------------------- pod admission

    def admit_pod(self, pod: t.Pod) -> "AdmitResult":
        """Verify assigned IDs + plugin AdmitPod RPC (manager.go:152-236).

        Infrastructure-not-ready conditions (plugin not yet discovered, first
        device frame not yet received, RPC transport failure) are RETRIABLE —
        a kubelet restart delivers bound pods before the 0.5s plugin scan
        completes, and failing them permanently would kill healthy workloads.
        Plugin vetoes and structural problems are permanent.
        """
        if not pod.spec.extended_resources:
            return AdmitResult(True, "", False)
        with self._lock:
            cached = self._admit_cache.get(pod.metadata.uid)
        if cached is not None:
            return AdmitResult(
                cached.get("allowed", False), cached.get("reason", ""), False
            )
        start = time.monotonic()
        by_resource: Dict[str, Dict[str, List[str]]] = {}
        for per in pod.spec.extended_resources:
            if not per.assigned:
                return AdmitResult(
                    False, f"extended resource {per.name} has no assignment", False
                )
            by_resource.setdefault(per.resource, {})[per.name] = per.assigned
        for resource, assignments in by_resource.items():
            with self._lock:
                ep = self._endpoints.get(resource)
                known = {d["id"]: d for d in self._store.get(resource, [])}
            if ep is None:
                return AdmitResult(False, f"no device plugin for {resource}", True)
            if not known:
                return AdmitResult(
                    False, f"no {resource} inventory received yet", True
                )
            for ids in assignments.values():
                for dev_id in ids:
                    dev = known.get(dev_id)
                    if dev is None:
                        return AdmitResult(
                            False,
                            f"assigned device {dev_id} not in local inventory",
                            False,
                        )
                    if dev.get("health") != t.DEVICE_HEALTHY:
                        return AdmitResult(
                            False, f"assigned device {dev_id} unhealthy", False
                        )
            try:
                result = ep.admit_pod(pod, assignments)
            except (ConnectionError, RuntimeError) as e:
                return AdmitResult(False, f"plugin AdmitPod failed: {e}", True)
            if not result.get("allowed", False):
                return AdmitResult(
                    False, result.get("reason", "plugin denied admission"), False
                )
        self.allocation_latency.observe(time.monotonic() - start)
        with self._lock:
            self._admit_cache[pod.metadata.uid] = {"allowed": True, "reason": ""}
        return AdmitResult(True, "", False)

    def init_container(self, pod: t.Pod, container: t.Container) -> ContainerSpec:
        """Merge plugin injections for every device request the container
        references (manager.go:245-291)."""
        merged = ContainerSpec()
        if not container.extended_resource_requests:
            return merged
        by_name = {per.name: per for per in pod.spec.extended_resources}
        for req_name in container.extended_resource_requests:
            per = by_name.get(req_name)
            if per is None or not per.assigned:
                continue
            with self._lock:
                ep = self._endpoints.get(per.resource)
            if ep is None:
                raise RuntimeError(f"no device plugin for {per.resource}")
            spec = ep.init_container(pod, container.name, per.assigned)
            merged.envs.update(spec.envs)
            merged.mounts.extend(spec.mounts)
            merged.devices.extend(spec.devices)
            merged.annotations.update(spec.annotations)
        return merged

    def forget_pod(self, pod_uid: str):
        """Lazy per-pod cache pruning (manager.go:293-310)."""
        with self._lock:
            self._admit_cache.pop(pod_uid, None)
