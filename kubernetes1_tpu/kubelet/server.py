"""Kubelet API server (ref: pkg/kubelet/server/server.go — the :10250
endpoint serving containerLogs/exec/stats/pods; auth there is delegated to
the apiserver, streaming rides SPDY via client-go/tools/remotecommand).

The TPU-native shape: a plain HTTP server per kubelet with
  GET  /healthz
  GET  /pods                                  pods this kubelet manages
  GET  /containerLogs/<ns>/<pod>/<container>  ?tail=N
  POST /exec/<ns>/<pod>/<container>           {"command": [...]}
       -> {"exitCode": N, "output": "..."}    (ExecSync, the probe seam)
  GET  /stats/summary                         node + per-pod usage
  GET  /metrics                               prometheus text

The node advertises the endpoint as the `kubelet.ktpu.io/server` annotation
on its Node object; `ktpu logs`/`ktpu exec` resolve it from there (the
reference publishes :10250 in nodeStatus.daemonEndpoints the same way).
An optional bearer token gates mutating verbs (exec).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class _KubeletHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ktpu-kubelet/0.1"
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def setup(self):
        # per-connection-thread TLS handshake (see apiserver _Handler.setup)
        handshake = getattr(self.request, "do_handshake", None)
        if handshake is not None:
            handshake()
        super().setup()

    @property
    def kubelet(self):
        return self.server.kubelet  # type: ignore[attr-defined]

    @property
    def token(self) -> str:
        return self.server.token  # type: ignore[attr-defined]

    def _send(self, code: int, payload, content_type="application/json"):
        raw = payload if isinstance(payload, bytes) else (
            json.dumps(payload).encode()
            if not isinstance(payload, str) else payload.encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _authorized(self) -> bool:
        if not self.token:
            return True
        import hmac

        # constant-time compare: the token grants command execution, so its
        # bytes must not leak via comparison timing (bytes, not str — str
        # compare_digest raises on non-ASCII header values)
        return hmac.compare_digest(
            self.headers.get("Authorization", "").encode("utf-8", "surrogateescape"),
            f"Bearer {self.token}".encode(),
        )

    def _resolve_container(self, ns: str, pod_name: str, cname: str):
        """(pod, container_id) or (None, error_response_sent)."""
        kl = self.kubelet
        pod = kl.pods.get(f"{ns}/{pod_name}")
        if pod is None:
            self._send(404, {"error": f"pod {ns}/{pod_name} not found on this node"})
            return None, None
        cname = cname or pod.spec.containers[0].name
        with kl._lock:
            cid = kl._containers.get((pod.metadata.uid, cname))
        if cid is None:
            self._send(404, {"error": f"container {cname!r} has no runtime record"})
            return None, None
        return pod, cid

    # ------------------------------------------------------------ streaming

    def _handle_stream(self, parts, rawq, q):
        """Upgraded bidirectional streams (ref: pkg/kubelet/server
        remotecommand exec/attach + portforward over SPDY; here the
        ktpu-stream channel protocol)."""
        from ..utils.streams import STDOUT, accept_upgrade, send_status, splice, write_frame

        kind = parts[0]
        if kind == "portForward":
            ns, pod_name = parts[1], parts[2]
            if self.kubelet.pods.get(f"{ns}/{pod_name}") is None:
                self._send(404, {"error": f"pod {ns}/{pod_name} not found on this node"})
                return
            port = int(q.get("port") or 0)
            if not port:
                self._send(400, {"error": "port required"})
                return
            import socket as _socket

            try:
                target = _socket.create_connection(("127.0.0.1", port), timeout=5)
            except OSError as e:
                self._send(502, {"error": f"connect 127.0.0.1:{port}: {e}"})
                return
            sock = accept_upgrade(self)
            if sock is None:
                target.close()
                self._send(400, {"error": "expected Upgrade: ktpu-stream"})
                return
            try:
                splice(sock, target)  # raw bytes, no framing — data is opaque
            finally:
                target.close()
            return

        ns, pod_name = parts[1], parts[2]
        cname = parts[3] if len(parts) > 3 else ""
        pod, cid = self._resolve_container(ns, pod_name, cname)
        if pod is None:
            return
        if kind == "attach":
            # ProcessRuntime containers write stdio to their log file;
            # attach = live follow of that stream (honest for a runtime
            # without a held-open stdio pipe)
            record = self.kubelet.runtime.container_status(cid)
            sock = accept_upgrade(self)
            if sock is None:
                self._send(400, {"error": "expected Upgrade: ktpu-stream"})
                return
            try:
                _follow_log(sock, self.kubelet.runtime, cid,
                            record.log_path if record else "")
            finally:
                sock.close()
            return

        # exec — validate the handshake BEFORE spawning: a bad Upgrade
        # header must not leak a running process
        command = rawq.get("command") or []
        if not command:
            self._send(400, {"error": "command required"})
            return
        if self.headers.get("Upgrade", "").lower() != "ktpu-stream":
            self._send(400, {"error": "expected Upgrade: ktpu-stream"})
            return
        tty = q.get("tty") in ("1", "true")
        stdin = q.get("stdin") in ("1", "true")
        res = self.kubelet.runtime.exec_stream(cid, command, tty=tty, stdin=stdin)
        if res is None:
            self._send(400, {"error": "runtime does not support streaming exec "
                                      "or container is not running"})
            return
        proc, master = res
        sock = accept_upgrade(self)
        if sock is None:  # defensive; header already validated above
            import os as _os

            proc.kill()
            proc.wait()
            if master is not None:
                try:
                    _os.close(master)
                except OSError:
                    pass
            self._send(400, {"error": "expected Upgrade: ktpu-stream"})
            return
        try:
            _pump_exec(sock, proc, master)
        finally:
            sock.close()

    def do_GET(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        rawq = parse_qs(parsed.query)
        q = {k: v[0] for k, v in rawq.items()}
        kl = self.kubelet
        try:
            if parts and parts[0] not in ("healthz", "readyz", "metrics") \
                    and not self._authorized():
                # everything that exposes workload data requires the token
                # the apiserver holds; only liveness/readiness + scrape
                # stay open
                self._send(401, {"error": "unauthorized"})
                return
            if parts and parts[0] in ("exec", "attach", "portForward") \
                    and self.headers.get("Upgrade"):
                self._handle_stream(parts, rawq, q)
                return
            if parts == ["healthz"]:
                self._send(200, {"status": "ok"})
            elif parts == ["readyz"]:
                # ready once the pod informer delivered its first LIST —
                # before that the kubelet can't know what it should be
                # running, and admitting traffic would report stale truth
                ready = kl.pods.has_synced()
                if ready:
                    self._send(200, {"status": "ok"})
                else:
                    self._send(503, {"status": "unready"})
            elif parts == ["debug", "traces"]:
                self._send(200, kl.spans.to_json(q.get("trace", "")),
                           content_type="application/json")
            elif parts == ["debug", "flightrecorder"]:
                from ..utils import flightrec

                self._send(200, flightrec.to_json(q.get("component", "")),
                           content_type="application/json")
            elif parts == ["pods"]:
                self._send(200, {"pods": sorted(p.key() for p in kl.pods.list())})
            elif parts and parts[0] == "containerLogs" and len(parts) >= 3:
                ns, pod_name = parts[1], parts[2]
                cname = parts[3] if len(parts) > 3 else ""
                pod, cid = self._resolve_container(ns, pod_name, cname)
                if pod is None:
                    return
                tail = int(q.get("tail") or 0)
                self._send(200, kl.runtime.read_log(cid, tail=tail),
                           content_type="text/plain")
            elif parts[:2] == ["stats", "summary"] or parts == ["stats"]:
                self._send(200, kl.stats_summary())
            elif parts == ["metrics"]:
                # ref pkg/kubelet/metrics/ + the fork's
                # DevicePluginAllocationLatency (manager.go:231) — the
                # signature metric must be scrapeable, not just recorded
                running = sum(
                    1 for c in kl.runtime.list_containers()
                    if c.state == "RUNNING"
                )
                body = (
                    f"# TYPE kubelet_running_pods gauge\n"
                    f"kubelet_running_pods {len(kl.pods.list())}\n"
                    f"# TYPE kubelet_running_containers gauge\n"
                    f"kubelet_running_containers {running}\n"
                    + kl.device_manager.allocation_latency.render()
                    # pod /metrics scrape health (custom-metrics plane):
                    # per-annotated-pod up/staleness — the node-local
                    # half the ObsCollector's scaling view federates
                    + kl.pod_scraper.render_metrics()
                )
                self._send(200, body, content_type="text/plain; version=0.0.4")
            else:
                self._send(404, {"error": f"unknown path {parsed.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send(500, {"error": str(e)})
            except OSError:
                pass  # client already disconnected

    def do_POST(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if parts and parts[0] == "exec" and len(parts) >= 3:
                if not self._authorized():
                    self._send(401, {"error": "unauthorized"})
                    return
                ns, pod_name = parts[1], parts[2]
                cname = parts[3] if len(parts) > 3 else ""
                pod, cid = self._resolve_container(ns, pod_name, cname)
                if pod is None:
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length)) if length else {}
                command = body.get("command") or []
                if not command:
                    self._send(400, {"error": "command required"})
                    return
                code, output = self.kubelet.runtime.exec_capture(cid, command)
                self._send(200, {"exitCode": code, "output": output})
            else:
                self._send(404, {"error": f"unknown path {parsed.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send(500, {"error": str(e)})
            except OSError:
                pass  # client already disconnected


def _pump_exec(sock, proc, master_fd):
    """Frame-pump a streaming exec: socket frames <-> process stdio.

    pty mode: one master fd carries both directions (tty semantics);
    pipe mode: stdout/stderr are separate channels.  Ends with a status
    frame carrying the exit code (the SPDY error-channel contract)."""
    import json as _json
    import os as _os
    import threading

    from ..utils.streams import (
        ERROR, RESIZE, STDERR, STDIN, STDOUT, read_frame, send_status,
        write_frame,
    )

    def sock_reader():
        """Client frames -> process stdin / resize."""
        try:
            while True:
                frame = read_frame(sock)
                if frame is None:
                    break
                channel, payload = frame
                if channel == STDIN:
                    if not payload:  # EOF
                        if master_fd is None and proc.stdin:
                            proc.stdin.close()
                        break
                    try:
                        if master_fd is not None:
                            _os.write(master_fd, payload)
                        elif proc.stdin:
                            proc.stdin.write(payload)
                            proc.stdin.flush()
                    except (OSError, ValueError, BrokenPipeError):
                        break
                elif channel == RESIZE and master_fd is not None:
                    try:
                        import fcntl
                        import struct as _struct
                        import termios

                        dims = _json.loads(payload)
                        fcntl.ioctl(master_fd, termios.TIOCSWINSZ, _struct.pack(
                            "HHHH", dims.get("rows", 24), dims.get("cols", 80), 0, 0))
                    except (OSError, ValueError, KeyError):
                        pass
        except OSError:
            pass

    t_in = threading.Thread(target=sock_reader, daemon=True)
    t_in.start()
    try:
        if master_fd is not None:
            while True:
                try:
                    data = _os.read(master_fd, 65536)
                except OSError:  # pty closes with EIO when the child exits
                    break
                if not data:
                    break
                write_frame(sock, STDOUT, data)
        else:
            def drain(f, channel):
                try:
                    while True:
                        data = f.read1(65536) if hasattr(f, "read1") else f.read(65536)
                        if not data:
                            break
                        write_frame(sock, channel, data)
                except (OSError, ValueError):
                    pass

            t_err = threading.Thread(
                target=drain, args=(proc.stderr, STDERR), daemon=True)
            t_err.start()
            drain(proc.stdout, STDOUT)
            t_err.join(timeout=5.0)
        code = proc.wait(timeout=30)
    except Exception as e:  # noqa: BLE001
        send_status(sock, -1, str(e))
        proc.kill()
        return
    finally:
        if master_fd is not None:
            try:
                _os.close(master_fd)
            except OSError:
                pass
    send_status(sock, code)


def _follow_log(sock, runtime, cid, log_path):
    """attach: stream log growth until the container exits or the client
    hangs up (a zero-byte read on the socket detects hangup)."""
    import os as _os
    import time as _time

    from ..utils import eventloop
    from ..utils.streams import STDOUT, send_status, write_frame

    from .runtime import CONTAINER_RUNNING

    if not log_path or not _os.path.exists(log_path):
        send_status(sock, -1, "no log stream for container")
        return
    with open(log_path, "rb") as f:
        f.seek(0, _os.SEEK_END)
        # replay a last-page tail so the attacher has context
        start = max(0, f.tell() - 4096)
        f.seek(start)
        while True:
            data = f.read(65536)
            if data:
                try:
                    write_frame(sock, STDOUT, data)
                except OSError:
                    return
                continue
            record = runtime.container_status(cid)
            if record is None or record.state != CONTAINER_RUNNING:
                send_status(sock, record.exit_code if record else -1)
                return
            # hangup detection: the client never sends frames on attach,
            # so any readable-EOF means it is gone (shared readiness
            # helper — utils/eventloop.wait_readable)
            if eventloop.wait_readable(sock, 0.25):
                probe = sock.recv(1)
                if not probe:
                    return
            _time.sleep(0.05)


class KubeletServer:
    """Owns the HTTP listener; the kubelet advertises `self.url` on its Node.

    With tls_cert_file set, the listener is HTTPS-only (the reference's
    kubelet serves :10250 over TLS with a CSR-issued serving cert) — the
    apiserver verifies it against the cluster CA on the exec/logs hop."""

    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0,
                 token: str = "", tls_cert_file: str = "",
                 tls_key_file: str = ""):
        self._httpd = ThreadingHTTPServer((host, port), _KubeletHandler)
        self._httpd.daemon_threads = True
        from ..utils.streams import quiet_connection_errors

        quiet_connection_errors(self._httpd)
        self._httpd.kubelet = kubelet  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        if tls_cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert_file,
                                keyfile=tls_key_file or None)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            self.url = f"https://{self.host}:{self.port}"
        else:
            self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="kubelet-server",
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
