"""Kubelet API server (ref: pkg/kubelet/server/server.go — the :10250
endpoint serving containerLogs/exec/stats/pods; auth there is delegated to
the apiserver, streaming rides SPDY via client-go/tools/remotecommand).

The TPU-native shape: a plain HTTP server per kubelet with
  GET  /healthz
  GET  /pods                                  pods this kubelet manages
  GET  /containerLogs/<ns>/<pod>/<container>  ?tail=N
  POST /exec/<ns>/<pod>/<container>           {"command": [...]}
       -> {"exitCode": N, "output": "..."}    (ExecSync, the probe seam)
  GET  /stats/summary                         node + per-pod usage
  GET  /metrics                               prometheus text

The node advertises the endpoint as the `kubelet.ktpu.io/server` annotation
on its Node object; `ktpu logs`/`ktpu exec` resolve it from there (the
reference publishes :10250 in nodeStatus.daemonEndpoints the same way).
An optional bearer token gates mutating verbs (exec).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class _KubeletHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ktpu-kubelet/0.1"
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def kubelet(self):
        return self.server.kubelet  # type: ignore[attr-defined]

    @property
    def token(self) -> str:
        return self.server.token  # type: ignore[attr-defined]

    def _send(self, code: int, payload, content_type="application/json"):
        raw = payload if isinstance(payload, bytes) else (
            json.dumps(payload).encode()
            if not isinstance(payload, str) else payload.encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _authorized(self) -> bool:
        if not self.token:
            return True
        import hmac

        # constant-time compare: the token grants command execution, so its
        # bytes must not leak via comparison timing (bytes, not str — str
        # compare_digest raises on non-ASCII header values)
        return hmac.compare_digest(
            self.headers.get("Authorization", "").encode("utf-8", "surrogateescape"),
            f"Bearer {self.token}".encode(),
        )

    def _resolve_container(self, ns: str, pod_name: str, cname: str):
        """(pod, container_id) or (None, error_response_sent)."""
        kl = self.kubelet
        pod = kl.pods.get(f"{ns}/{pod_name}")
        if pod is None:
            self._send(404, {"error": f"pod {ns}/{pod_name} not found on this node"})
            return None, None
        cname = cname or pod.spec.containers[0].name
        with kl._lock:
            cid = kl._containers.get((pod.metadata.uid, cname))
        if cid is None:
            self._send(404, {"error": f"container {cname!r} has no runtime record"})
            return None, None
        return pod, cid

    def do_GET(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        kl = self.kubelet
        try:
            if parts == ["healthz"]:
                self._send(200, {"status": "ok"})
            elif parts == ["pods"]:
                self._send(200, {"pods": sorted(p.key() for p in kl.pods.list())})
            elif parts and parts[0] == "containerLogs" and len(parts) >= 3:
                ns, pod_name = parts[1], parts[2]
                cname = parts[3] if len(parts) > 3 else ""
                pod, cid = self._resolve_container(ns, pod_name, cname)
                if pod is None:
                    return
                tail = int(q.get("tail") or 0)
                self._send(200, kl.runtime.read_log(cid, tail=tail),
                           content_type="text/plain")
            elif parts[:2] == ["stats", "summary"] or parts == ["stats"]:
                self._send(200, kl.stats_summary())
            elif parts == ["metrics"]:
                body = (
                    f"# TYPE kubelet_running_pods gauge\n"
                    f"kubelet_running_pods {len(kl.pods.list())}\n"
                )
                self._send(200, body, content_type="text/plain; version=0.0.4")
            else:
                self._send(404, {"error": f"unknown path {parsed.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send(500, {"error": str(e)})
            except Exception:  # noqa: BLE001
                pass

    def do_POST(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if parts and parts[0] == "exec" and len(parts) >= 3:
                if not self._authorized():
                    self._send(401, {"error": "unauthorized"})
                    return
                ns, pod_name = parts[1], parts[2]
                cname = parts[3] if len(parts) > 3 else ""
                pod, cid = self._resolve_container(ns, pod_name, cname)
                if pod is None:
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length)) if length else {}
                command = body.get("command") or []
                if not command:
                    self._send(400, {"error": "command required"})
                    return
                code, output = self.kubelet.runtime.exec_capture(cid, command)
                self._send(200, {"exitCode": code, "output": output})
            else:
                self._send(404, {"error": f"unknown path {parsed.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send(500, {"error": str(e)})
            except Exception:  # noqa: BLE001
                pass


class KubeletServer:
    """Owns the HTTP listener; the kubelet advertises `self.url` on its Node."""

    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0,
                 token: str = ""):
        self._httpd = ThreadingHTTPServer((host, port), _KubeletHandler)
        self._httpd.daemon_threads = True
        self._httpd.kubelet = kubelet  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="kubelet-server",
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
