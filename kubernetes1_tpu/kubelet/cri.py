"""CRI over a unix socket: the kubelet↔runtime process boundary.

Ref: pkg/kubelet/apis/cri/v1alpha1/runtime/api.proto (RuntimeService 20
RPCs over a unix-socket gRPC server), pkg/kubelet/remote/ (client),
pkg/kubelet/dockershim (server wrapping a concrete runtime).

Round 2 left the CRI seam in-process (a Python ABC); this module gives it
the same transport treatment the device-plugin API got: newline-delimited
JSON frames over AF_UNIX (grpcio is not in this image; the protocol seams
are what matter).  Any RuntimeService implementation can be served:

    server = RuntimeServer(ProcessRuntime(root_dir=...), "/run/ktpu/cri.sock")
    server.start()
    kubelet = Kubelet(cs, node, runtime=RemoteRuntime("/run/ktpu/cri.sock"))

so the runtime can live in a different process (or a different language —
the wire format is trivially speakable from C++), exactly like containerd
vs kubelet in the reference.

Wire format (same as deviceplugin/api.py):
  request:  {"id": N, "method": "...", "params": {...}}\n
  response: {"id": N, "result": ...} | {"id": N, "error": "..."}\n

exec_stream is intentionally not proxied: the reference's CRI returns a
streaming URL from Exec() and the kubelet server dials it; here the
interactive path lives in the kubelet server already, and a remote runtime
serves one-shot exec (exec_capture) — streaming exec against a remote
runtime degrades to capture, as dockershim's ExecSync does.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import traceback
from typing import Dict, List, Optional

from ..client.retry import Backoff
from ..utils import faultline, locksan
from .runtime import (
    ContainerConfig,
    ContainerRecord,
    RuntimeService,
    SandboxRecord,
)


def _sandbox_to_dict(s: SandboxRecord) -> dict:
    return vars(s).copy()


def _container_to_dict(c: ContainerRecord) -> dict:
    return vars(c).copy()


# A method table keeps dispatch explicit (no getattr-on-wire-data).
_METHODS = (
    "capabilities",
    "version",
    "run_pod_sandbox",
    "stop_pod_sandbox",
    "remove_pod_sandbox",
    "list_pod_sandboxes",
    "create_container",
    "start_container",
    "stop_container",
    "remove_container",
    "list_containers",
    "container_status",
    "read_log",
    "container_stats",
    "exec_in_container",
    "exec_capture",
    "set_container_affinity",
    "pull_image",
    "list_images",
    "image_present",
)


class RuntimeServer:
    """Serves a RuntimeService over a unix socket (the dockershim role)."""

    def __init__(self, runtime: RuntimeService, socket_path: str):
        self.runtime = runtime
        self.socket_path = socket_path
        self._stop = threading.Event()
        os.makedirs(os.path.dirname(socket_path) or ".", exist_ok=True)
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(socket_path)
        self._sock.listen(16)

    def start(self):
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="cri-server").start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        f = conn.makefile("rwb")
        try:
            for line in f:
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    break
                rid = req.get("id")
                try:
                    result = self._dispatch(req.get("method"),
                                            req.get("params") or {})
                    f.write(json.dumps({"id": rid, "result": result}).encode()
                            + b"\n")
                except Exception as e:  # noqa: BLE001
                    traceback.print_exc()
                    f.write(json.dumps({"id": rid, "error": str(e)}).encode()
                            + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, method: Optional[str], params: dict):
        if method not in _METHODS:
            raise ValueError(f"unknown CRI method {method!r}")
        rt = self.runtime
        if method == "capabilities":
            # the kubelet gates cgroup enforcement + CPU pinning on
            # real_pids; a remote ProcessRuntime must advertise it or the
            # identical runtime silently loses enforcement across the socket.
            # default_uid: the identity a container with no runAsUser execs
            # as — the kubelet's runAsNonRoot verification needs the
            # RUNTIME's euid, not its own (they can differ across the socket)
            return {"real_pids": bool(getattr(rt, "real_pids", False)),
                    "root": getattr(rt, "root", None),
                    "default_uid": getattr(rt, "default_uid", None)}
        if method == "version":
            return rt.version()
        if method == "run_pod_sandbox":
            return rt.run_pod_sandbox(
                params["pod_name"], params["pod_namespace"], params["pod_uid"],
                labels=params.get("labels"))
        if method == "stop_pod_sandbox":
            return rt.stop_pod_sandbox(params["sandbox_id"])
        if method == "remove_pod_sandbox":
            return rt.remove_pod_sandbox(params["sandbox_id"])
        if method == "list_pod_sandboxes":
            return [_sandbox_to_dict(s) for s in rt.list_pod_sandboxes()]
        if method == "create_container":
            cfg = ContainerConfig(**params["config"])
            return rt.create_container(params["sandbox_id"], cfg)
        if method == "start_container":
            return rt.start_container(params["container_id"])
        if method == "stop_container":
            return rt.stop_container(params["container_id"],
                                     timeout=params.get("timeout", 10.0))
        if method == "remove_container":
            return rt.remove_container(params["container_id"])
        if method == "list_containers":
            return [_container_to_dict(c) for c in rt.list_containers()]
        if method == "container_status":
            rec = rt.container_status(params["container_id"])
            return _container_to_dict(rec) if rec is not None else None
        if method == "read_log":
            return rt.read_log(params["container_id"],
                               tail=params.get("tail", 0))
        if method == "container_stats":
            return rt.container_stats(params["container_id"])
        if method == "exec_in_container":
            return rt.exec_in_container(params["container_id"],
                                        params["command"])
        if method == "exec_capture":
            code, out = rt.exec_capture(params["container_id"],
                                        params["command"])
            return {"exit_code": code, "output": out}
        if method == "set_container_affinity":
            return rt.set_container_affinity(params["container_id"],
                                             set(params["cpus"]))
        # ImageService RPCs (ref api.proto ImageService) proxy to the
        # runtime's image service when it has one
        images = getattr(rt, "images", None)
        if method == "pull_image":
            return images.pull_image(params["image"]) if images else ""
        if method == "list_images":
            return images.list_images() if images else []
        if method == "image_present":
            return images.image_present(params["image"]) if images else False
        raise ValueError(f"unhandled CRI method {method!r}")


class RemoteRuntime(RuntimeService):
    """Kubelet-side RuntimeService speaking the socket protocol (the
    pkg/kubelet/remote role).  Reconnects per broken pipe; one in-flight
    call per connection (the kubelet's sync workers each get their own
    socket via a small pool)."""

    def __init__(self, socket_path: str, timeout: float = 30.0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._pool: List = []
        self._lock = locksan.make_lock("RemoteRuntime._lock")
        self._next_id = 0
        self._caps: Optional[dict] = None
        self._ever_connected = False

    def _capabilities(self) -> dict:
        if self._caps is None:
            try:
                self._caps = self._call("capabilities") or {}
            except (ConnectionError, OSError, RuntimeError):
                # server not up yet: report nothing special, but don't cache
                # the failure — the kubelet may ask again once it is
                return {}
        return self._caps

    @property
    def real_pids(self) -> bool:
        """Mirrors the wrapped runtime (queried over the socket) so the
        kubelet's cgroup/CPU-manager gating behaves identically for a
        remote ProcessRuntime (see _dispatch 'capabilities')."""
        return bool(self._capabilities().get("real_pids", False))

    @property
    def root(self):
        return self._capabilities().get("root")

    @property
    def default_uid(self):
        """The runtime daemon's euid — what a no-runAsUser container execs
        as over there.  None until the runtime has answered capabilities;
        the kubelet treats unknown as fail-closed for runAsNonRoot."""
        return self._capabilities().get("default_uid")

    @property
    def identity_known(self) -> bool:
        """True once capabilities HAVE been answered — lets the kubelet
        tell 'runtime not up yet' (transient, defer) from 'runtime answered
        without an identity' (version skew: permanent, fail the pod with a
        real error instead of deferring forever)."""
        self._capabilities()
        return self._caps is not None

    # ----------------------------------------------------------- transport

    def _connect(self, retry_window: float = 5.0):
        # Bounded dial retry ONLY until the first successful connection: the
        # runtime is typically spawned concurrently with the kubelet and its
        # listener may lag by a beat (upstream kubelet blocks on the CRI
        # socket too, cmd/kubelet/app/server.go).  Once the runtime has been
        # reachable, reconnects fail fast — a crashed runtime must not turn
        # every PLEG relist into a 5s blocking loop.
        deadline = time.monotonic() + (
            retry_window if not self._ever_connected else 0.0)
        backoff = Backoff(base=0.02, factor=2.0, cap=0.2)
        while True:
            faultline.check("cri.dial")  # before the fd exists — a drop must not leak a socket
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(self.timeout)
            try:
                conn.connect(self.socket_path)
                self._ever_connected = True
                return conn, conn.makefile("rwb")
            except (ConnectionRefusedError, FileNotFoundError):
                conn.close()
                if time.monotonic() >= deadline:
                    raise
                backoff.sleep()

    def _call(self, method: str, params: Optional[dict] = None):
        with self._lock:
            pair = self._pool.pop() if self._pool else None
            self._next_id += 1
            rid = self._next_id
        if pair is None:
            pair = self._connect()
        conn, f = pair
        frame = json.dumps({"id": rid, "method": method,
                            "params": params or {}})
        try:
            f.write(frame.encode() + b"\n")
            f.flush()
            line = f.readline()
        except (BrokenPipeError, ConnectionResetError, OSError):
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(f"CRI runtime {self.socket_path} unreachable")
        if not line:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(f"CRI runtime {self.socket_path} closed")
        # Parse + match the response id BEFORE re-pooling: a corrupt or
        # misaligned frame means this connection is desynchronized and must
        # not be reused by a later call.
        try:
            resp = json.loads(line)
        except ValueError:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(
                f"CRI runtime {self.socket_path}: corrupt response frame")
        if resp.get("id") != rid:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(
                f"CRI runtime {self.socket_path}: response id mismatch "
                f"(got {resp.get('id')!r}, want {rid})")
        with self._lock:
            self._pool.append(pair)
        if resp.get("error"):
            raise RuntimeError(f"CRI {method}: {resp['error']}")
        return resp.get("result")

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for conn, _f in pool:
            try:
                conn.close()
            except OSError:
                pass

    # -------------------------------------------------------- RuntimeService

    def version(self) -> str:
        return self._call("version")

    def run_pod_sandbox(self, pod_name, pod_namespace, pod_uid, labels=None) -> str:
        return self._call("run_pod_sandbox", {
            "pod_name": pod_name, "pod_namespace": pod_namespace,
            "pod_uid": pod_uid, "labels": labels})

    def stop_pod_sandbox(self, sandbox_id: str):
        self._call("stop_pod_sandbox", {"sandbox_id": sandbox_id})

    def remove_pod_sandbox(self, sandbox_id: str):
        self._call("remove_pod_sandbox", {"sandbox_id": sandbox_id})

    def list_pod_sandboxes(self) -> List[SandboxRecord]:
        return [SandboxRecord(**d) for d in self._call("list_pod_sandboxes")]

    def create_container(self, sandbox_id: str, config: ContainerConfig) -> str:
        return self._call("create_container", {
            "sandbox_id": sandbox_id, "config": vars(config).copy()})

    def start_container(self, container_id: str):
        self._call("start_container", {"container_id": container_id})

    def stop_container(self, container_id: str, timeout: float = 10.0):
        self._call("stop_container", {"container_id": container_id,
                                      "timeout": timeout})

    def remove_container(self, container_id: str):
        self._call("remove_container", {"container_id": container_id})

    def list_containers(self) -> List[ContainerRecord]:
        return [ContainerRecord(**d) for d in self._call("list_containers")]

    def container_status(self, container_id: str) -> Optional[ContainerRecord]:
        d = self._call("container_status", {"container_id": container_id})
        return ContainerRecord(**d) if d is not None else None

    def read_log(self, container_id: str, tail: int = 0) -> str:
        return self._call("read_log", {"container_id": container_id,
                                       "tail": tail})

    def container_stats(self, container_id: str) -> Dict[str, float]:
        return self._call("container_stats", {"container_id": container_id})

    def exec_in_container(self, container_id: str, command) -> int:
        return self._call("exec_in_container", {
            "container_id": container_id, "command": list(command)})

    def exec_capture(self, container_id: str, command) -> tuple:
        d = self._call("exec_capture", {"container_id": container_id,
                                        "command": list(command)})
        return d["exit_code"], d["output"]

    def set_container_affinity(self, container_id: str, cpus) -> bool:
        return bool(self._call("set_container_affinity", {
            "container_id": container_id, "cpus": sorted(cpus)}))

    @property
    def images(self) -> "_RemoteImages":
        """ImageService facade over the socket — imagePullPolicy handling
        and the kubelet's node.status.images inventory both work for
        remote runtimes exactly as for in-process ones."""
        return _RemoteImages(self)


class _RemoteImages:
    def __init__(self, rt: RemoteRuntime):
        self._rt = rt

    def pull_image(self, image: str) -> str:
        return self._rt._call("pull_image", {"image": image})

    def list_images(self) -> List[str]:
        return self._rt._call("list_images") or []

    def image_present(self, image: str) -> bool:
        return bool(self._rt._call("image_present", {"image": image}))
