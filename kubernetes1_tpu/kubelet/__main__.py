"""Standalone kubelet entrypoint (ref: cmd/kubelet).

    python -m kubernetes1_tpu.kubelet --server http://127.0.0.1:8001 \
        --node-name $(hostname) --runtime process --plugin-dir /var/lib/ktpu/device-plugins
"""

import argparse
import signal
import threading

from ..deviceplugin.api import DEFAULT_PLUGIN_DIR
from .kubelet import Kubelet
from .runtime import FakeRuntime, ProcessRuntime


def main():
    ap = argparse.ArgumentParser(description="ktpu kubelet")
    ap.add_argument("--feature-gates", default="", help="Name=true|false list (one shared gate map; utils/features.py)")
    ap.add_argument("--server", default="http://127.0.0.1:8001")
    ap.add_argument("--token", default="")
    ap.add_argument("--node-name", default="node-0")
    ap.add_argument("--runtime", choices=["process", "fake"], default="process")
    ap.add_argument("--plugin-dir", default=DEFAULT_PLUGIN_DIR)
    ap.add_argument("--static-pod-dir", default="")
    ap.add_argument("--root-dir", default="/tmp/ktpu")
    ap.add_argument("--label", action="append", default=[], help="k=v node label")
    ap.add_argument("--container-runtime-endpoint", default="",
                    help="unix socket of a remote CRI runtime (e.g. the "
                         "native ktpu-cri-runtime); overrides --runtime")
    ap.add_argument("--cpu-manager-policy", choices=["none", "static"],
                    default="none")
    ap.add_argument("--tls-cert-file", default="",
                    help="serving cert for the kubelet server (:10250 TLS)")
    ap.add_argument("--tls-key-file", default="")
    from ..utils.procutil import add_client_args, clientset_from_args

    add_client_args(ap)
    args = ap.parse_args()
    if args.feature_gates:
        from ..utils.features import gates
        gates.apply(args.feature_gates)

    cs = clientset_from_args(args)
    if args.container_runtime_endpoint:
        from .cri import RemoteRuntime

        runtime = RemoteRuntime(args.container_runtime_endpoint)
    elif args.runtime == "process":
        runtime = ProcessRuntime(root_dir=args.root_dir)
    else:
        runtime = FakeRuntime()
    labels = dict(kv.split("=", 1) for kv in args.label)
    kubelet = Kubelet(
        cs,
        node_name=args.node_name,
        runtime=runtime,
        plugin_dir=args.plugin_dir,
        static_pod_dir=args.static_pod_dir or None,
        node_labels=labels,
        cpu_manager_policy=args.cpu_manager_policy,
        server_tls_cert_file=args.tls_cert_file,
        server_tls_key_file=args.tls_key_file,
    )
    kubelet.start()
    runtime_desc = (f"remote CRI {args.container_runtime_endpoint}"
                    if args.container_runtime_endpoint else
                    f"{args.runtime} runtime")
    print(f"kubelet {args.node_name} running ({runtime_desc})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    from ..utils.procutil import bounded_exit

    bounded_exit(5.0)
    kubelet.stop()


if __name__ == "__main__":
    main()
