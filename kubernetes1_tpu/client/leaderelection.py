"""Leader election via CAS on a Lease object.

Ref: client-go tools/leaderelection/leaderelection.go:138-274 — the same
acquire/renew loop over a resource lock: candidates try to create/update the
Lease; the holder renews every retry_period; takers steal only after
lease_duration since the last observed renewal.  Non-leaders hot-standby.
"""

from __future__ import annotations

import http.client
import threading
import time
import traceback
from typing import Callable, Optional

from ..api import types as t
from ..machinery.errors import AlreadyExists, ApiError, Conflict, NotFound
from ..machinery.meta import now_iso_micro, parse_iso
from .clientset import Clientset


class LeaderElector:
    def __init__(
        self,
        clientset: Clientset,
        name: str,
        identity: str,
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.cs = clientset
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._observed_renew: dict = {}
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def wait_for_leadership(self, timeout: float = 10.0) -> bool:
        return self._is_leader.wait(timeout)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._is_leader.is_set():
            self._release()

    # ----------------------------------------------------------------- loop

    def _run(self):
        while not self._stop.is_set():
            try:
                if self._try_acquire_or_renew():
                    if not self._is_leader.is_set():
                        self._is_leader.set()
                        if self.on_started_leading:
                            self.on_started_leading()
                else:
                    if self._is_leader.is_set():
                        self._is_leader.clear()
                        if self.on_stopped_leading:
                            self.on_stopped_leading()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            self._stop.wait(self.retry_period)

    def _try_acquire_or_renew(self) -> bool:
        now = now_iso_micro()
        try:
            lease = self.cs.leases.get(self.name, self.namespace)
        except NotFound:
            lease = t.Lease()
            lease.metadata.name = self.name
            lease.metadata.namespace = self.namespace
            lease.holder_identity = self.identity
            lease.lease_duration_seconds = int(self.lease_duration)
            lease.acquire_time = now
            lease.renew_time = now
            try:
                self.cs.leases.create(lease, self.namespace)
                return True
            except AlreadyExists:
                return False

        if lease.holder_identity == self.identity:
            lease.renew_time = now
            try:
                self.cs.leases.update(lease)
                return True
            except Conflict:
                return False

        # Another holder: steal only if its renewal is stale.  Renew times are
        # wall-clock ISO strings; with second resolution a fresh lease parses
        # equal to "now", which is fine at these timescales.
        if lease.renew_time and not self._expired(lease):
            return False
        lease.holder_identity = self.identity
        lease.acquire_time = now
        lease.renew_time = now
        lease.lease_transitions += 1
        try:
            self.cs.leases.update(lease)
            return True
        except Conflict:
            return False

    def _expired(self, lease: t.Lease) -> bool:
        renew = parse_iso(lease.renew_time)  # UTC, microsecond resolution
        return (time.time() - renew) > max(  # ktpulint: ignore[KTPU005] cross-process lease timestamp
            float(lease.lease_duration_seconds), self.lease_duration
        )

    def _release(self):
        try:
            lease = self.cs.leases.get(self.name, self.namespace)
            if lease.holder_identity == self.identity:
                lease.holder_identity = ""
                self.cs.leases.update(lease)
        except (ApiError, OSError, http.client.HTTPException):
            pass  # best-effort release on shutdown; lease expires anyway
