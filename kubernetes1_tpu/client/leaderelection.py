"""Leader election via CAS on a Lease object — singleton and sharded.

Ref: client-go tools/leaderelection/leaderelection.go:138-274 — the same
acquire/renew loop over a resource lock: candidates try to create/update the
Lease; the holder renews every retry_period; takers steal only after
lease_duration since the last observed renewal.  Non-leaders hot-standby.

``LeaseSet`` generalizes the machinery from ONE lease to a numbered set of
shard leases (the scheduler's parallel-actor decomposition): every instance
announces itself with a member lease, the live members partition the shard
set by rendezvous hashing, and each instance claims its shards, steals
expired ones, and hot-standbys the rest — an instance death moves its
shards to the survivors within one lease_duration, with the same CAS
guarantees as singleton election.  All lease traffic rides the ordinary
clientset, so it inherits the client.* faultline sites and retry policy.
"""

from __future__ import annotations

import http.client
import threading
import time
import traceback
import zlib
from typing import Callable, Dict, FrozenSet, Optional

from ..api import types as t
from ..machinery.errors import AlreadyExists, ApiError, Conflict, NotFound
from ..machinery.meta import now_iso_micro, parse_iso
from ..utils import flightrec
from .clientset import Clientset


class LeaderElector:
    def __init__(
        self,
        clientset: Clientset,
        name: str,
        identity: str,
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.cs = clientset
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._observed_renew: dict = {}
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def wait_for_leadership(self, timeout: float = 10.0) -> bool:
        return self._is_leader.wait(timeout)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._is_leader.is_set():
            self._release()

    # ----------------------------------------------------------------- loop

    def _run(self):
        while not self._stop.is_set():
            try:
                if self._try_acquire_or_renew():
                    if not self._is_leader.is_set():
                        self._is_leader.set()
                        if self.on_started_leading:
                            self.on_started_leading()
                else:
                    if self._is_leader.is_set():
                        self._is_leader.clear()
                        if self.on_stopped_leading:
                            self.on_stopped_leading()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            self._stop.wait(self.retry_period)

    def _try_acquire_or_renew(self) -> bool:
        now = now_iso_micro()
        try:
            lease = self.cs.leases.get(self.name, self.namespace)
        except NotFound:
            lease = t.Lease()
            lease.metadata.name = self.name
            lease.metadata.namespace = self.namespace
            lease.holder_identity = self.identity
            lease.lease_duration_seconds = int(self.lease_duration)
            lease.acquire_time = now
            lease.renew_time = now
            try:
                self.cs.leases.create(lease, self.namespace)
                return True
            except AlreadyExists:
                return False

        if lease.holder_identity == self.identity:
            lease.renew_time = now
            try:
                self.cs.leases.update(lease)
                return True
            except Conflict:
                return False

        # Another holder: steal only if its renewal is stale.  Renew times are
        # wall-clock ISO strings; with second resolution a fresh lease parses
        # equal to "now", which is fine at these timescales.
        if lease.renew_time and not self._expired(lease):
            return False
        lease.holder_identity = self.identity
        lease.acquire_time = now
        lease.renew_time = now
        lease.lease_transitions += 1
        try:
            self.cs.leases.update(lease)
            return True
        except Conflict:
            return False

    def _expired(self, lease: t.Lease) -> bool:
        renew = parse_iso(lease.renew_time)  # UTC, microsecond resolution
        return (time.time() - renew) > max(  # ktpulint: ignore[KTPU005] cross-process lease timestamp
            float(lease.lease_duration_seconds), self.lease_duration
        )

    def _release(self):
        try:
            lease = self.cs.leases.get(self.name, self.namespace)
            if lease.holder_identity == self.identity:
                lease.holder_identity = ""
                self.cs.leases.update(lease)
        except (ApiError, OSError, http.client.HTTPException):
            pass  # best-effort release on shutdown; lease expires anyway


def _rendezvous_score(identity: str, shard: int) -> int:
    """Stable per-(identity, shard) weight: the LIVE identity with the
    highest score is the shard's preferred owner.  crc32, not hash() —
    Python's hash is salted per process and the instances must agree."""
    return zlib.crc32(f"{identity}:{shard}".encode())


class LeaseSet:
    """Shard-lease acquisition: N shard leases partitioned across however
    many live instances exist, built from the same CAS-on-Lease primitive
    as LeaderElector.

    Topology discovery rides MEMBER leases (one per instance, renewed
    every retry_period): an instance is "live" while its member lease is
    unexpired.  Each live instance then wants the shards whose rendezvous
    winner it is — roughly shards/instances each, recomputed as members
    come and go:

      - it CLAIMS a wanted shard whenever the shard lease is unheld,
        released, or expired (a dead owner's lease expires after
        lease_duration — the steal path);
      - it SHEDS a held shard whose rendezvous winner is a DIFFERENT live
        instance (holder -> ""), so a newly-joined instance picks up its
        share within ~2 retry periods;
      - as an availability net it also claims UNWANTED shards that have
        sat unheld/expired past a full lease_duration (the designated
        winner never showed up or wedged) — a shard is never orphaned
        just because its preferred owner is gone;
      - everything else it HOT-STANDBYS: watching the leases, ready to
        steal.

    With one instance the rendezvous winner of every shard is that
    instance, so it owns the full set — shards=1 single-instance behaves
    exactly like LeaderElector with extra steps skipped.

    on_acquired(shard)/on_lost(shard) fire from the renew thread, outside
    any lock; owned() is the race-free snapshot consumers read per
    decision."""

    def __init__(
        self,
        clientset: Clientset,
        name: str,
        identity: str,
        shards: int,
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        on_acquired: Optional[Callable[[int], None]] = None,
        on_lost: Optional[Callable[[int], None]] = None,
    ):
        self.cs = clientset
        self.name = name
        self.identity = identity
        self.shards = int(shards)
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self._stop = threading.Event()
        self._owned: FrozenSet[int] = frozenset()
        self._owned_event = threading.Event()  # set while owning >= 1 shard
        self._unheld_since: Dict[int, float] = {}
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- observers

    def owned(self) -> FrozenSet[int]:
        """Current shard ownership (atomic snapshot; replaced wholesale)."""
        return self._owned

    def wait_for_any(self, timeout: float = 10.0) -> bool:
        return self._owned_event.wait(timeout)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "LeaseSet":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"leaseset-{self.identity}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # best-effort release so successors steal instantly instead of
        # waiting out lease_duration; the member lease just expires
        for shard in self._owned:
            try:
                lease = self.cs.leases.get(self._shard_lease_name(shard),
                                           self.namespace)
                if lease.holder_identity == self.identity:
                    lease.holder_identity = ""
                    self.cs.leases.update(lease)
            except (ApiError, OSError, http.client.HTTPException):
                pass

    # ----------------------------------------------------------- internals

    def _member_lease_name(self, identity: str) -> str:
        return f"{self.name}-member-{identity}"

    def _shard_lease_name(self, shard: int) -> str:
        return f"{self.name}-shard-{shard}"

    def _expired(self, lease: t.Lease) -> bool:
        if not lease.renew_time:
            return True
        renew = parse_iso(lease.renew_time)  # UTC, microsecond resolution
        return (time.time() - renew) > max(  # ktpulint: ignore[KTPU005] cross-process lease timestamp
            float(lease.lease_duration_seconds), self.lease_duration
        )

    def _upsert_member(self):
        now = now_iso_micro()
        name = self._member_lease_name(self.identity)
        try:
            lease = self.cs.leases.get(name, self.namespace)
        except NotFound:
            lease = t.Lease()
            lease.metadata.name = name
            lease.metadata.namespace = self.namespace
            lease.holder_identity = self.identity
            lease.lease_duration_seconds = int(self.lease_duration)
            lease.acquire_time = now
            lease.renew_time = now
            try:
                self.cs.leases.create(lease, self.namespace)
            except AlreadyExists:
                pass
            return
        lease.holder_identity = self.identity
        lease.renew_time = now
        try:
            self.cs.leases.update(lease)
        except Conflict:
            pass  # next tick retries; identity-named, nobody else writes it

    def _snapshot(self):
        """One LIST: live member identities + shard lease objects."""
        items, _rv = self.cs.leases.list(namespace=self.namespace)
        member_prefix = f"{self.name}-member-"
        live = {self.identity}
        shard_leases: Dict[int, t.Lease] = {}
        for lease in items:
            n = lease.metadata.name
            if n.startswith(member_prefix):
                if lease.holder_identity and not self._expired(lease):
                    live.add(lease.holder_identity)
            elif n.startswith(f"{self.name}-shard-"):
                try:
                    idx = int(n.rsplit("-", 1)[1])
                except ValueError:
                    continue
                if 0 <= idx < self.shards:
                    shard_leases[idx] = lease
        return live, shard_leases

    def _winner(self, shard: int, live) -> str:
        return max(sorted(live),
                   key=lambda ident: _rendezvous_score(ident, shard))

    def _try_take(self, shard: int, lease: Optional[t.Lease]) -> bool:
        now = now_iso_micro()
        if lease is None:
            lease = t.Lease()
            lease.metadata.name = self._shard_lease_name(shard)
            lease.metadata.namespace = self.namespace
            lease.holder_identity = self.identity
            lease.lease_duration_seconds = int(self.lease_duration)
            lease.acquire_time = now
            lease.renew_time = now
            try:
                self.cs.leases.create(lease, self.namespace)
                return True
            except AlreadyExists:
                return False
        lease.holder_identity = self.identity
        lease.acquire_time = now
        lease.renew_time = now
        lease.lease_transitions += 1
        try:
            self.cs.leases.update(lease)
            return True
        except Conflict:
            return False  # raced another taker; CAS decided

    def _renew(self, lease: t.Lease) -> bool:
        lease.renew_time = now_iso_micro()
        try:
            self.cs.leases.update(lease)
            return True
        except Conflict:
            return False  # someone stole it (we were presumed dead)

    def _release_shard(self, lease: t.Lease):
        lease.holder_identity = ""
        try:
            self.cs.leases.update(lease)
        except Conflict:
            pass  # racer already took it — same outcome

    def _tick(self):
        self._upsert_member()
        live, shard_leases = self._snapshot()
        now = time.monotonic()
        next_owned = set()
        for shard in range(self.shards):
            lease = shard_leases.get(shard)
            holder = lease.holder_identity if lease is not None else ""
            held_by_me = holder == self.identity
            expired = lease is None or not holder or self._expired(lease)
            winner = self._winner(shard, live)
            if expired:
                self._unheld_since.setdefault(shard, now)
            else:
                self._unheld_since.pop(shard, None)
            if held_by_me and not self._expired(lease):
                if winner != self.identity and winner in live:
                    # shed: the rendezvous winner is a live peer — hand
                    # the shard over so a joining instance gets its share
                    self._release_shard(lease)
                    flightrec.note("scheduler", flightrec.LEASE_SHED,
                                   shard=shard, identity=self.identity,
                                   to=winner)
                    continue
                if self._renew(lease):
                    next_owned.add(shard)
                continue
            if not expired:
                continue  # live peer holds it: hot-standby
            stolen_from = (lease.holder_identity
                           if lease is not None else "")
            if winner == self.identity:
                if self._try_take(shard, lease):
                    next_owned.add(shard)
                    self._unheld_since.pop(shard, None)
                    if stolen_from and stolen_from != self.identity:
                        flightrec.note(
                            "scheduler", flightrec.LEASE_STEAL,
                            shard=shard, identity=self.identity,
                            from_=stolen_from)
            elif now - self._unheld_since.get(shard, now) \
                    > self.lease_duration:
                # availability net: the designated winner never claimed
                # it for a full lease_duration — any live instance takes
                # an orphan over leaving its pods unscheduled
                if self._try_take(shard, lease):
                    next_owned.add(shard)
                    self._unheld_since.pop(shard, None)
                    flightrec.note(
                        "scheduler", flightrec.LEASE_STEAL,
                        shard=shard, identity=self.identity,
                        from_=stolen_from or "(orphan)")
        self._apply_ownership(frozenset(next_owned))

    def _apply_ownership(self, next_owned: FrozenSet[int]):
        prev, self._owned = self._owned, next_owned
        if next_owned:
            self._owned_event.set()
        else:
            self._owned_event.clear()
        for shard in sorted(next_owned - prev):
            if self.on_acquired:
                self.on_acquired(shard)
        for shard in sorted(prev - next_owned):
            if self.on_lost:
                self.on_lost(shard)

    def _run(self):
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            self._stop.wait(self.retry_period)
