"""Event recorder (ref: client-go tools/record) — best-effort, rate-bounded
event creation with count aggregation for repeats.

Like the reference's EventBroadcaster, recording is ASYNCHRONOUS: event()
enqueues onto a bounded buffer drained by one background sink thread
(client-go's StartRecordingToSink over a buffered channel), so an event
never adds an API round trip to the caller's hot path — the scheduler's
bind loop and the kubelet's sync workers record thousands of events under
load.  When the buffer is full the newest event is dropped (events are
best-effort by contract; upstream's channel send behaves the same)."""

from __future__ import annotations

import queue
import threading
from typing import Dict

from ..api import types as t
from ..machinery import now_iso
from ..utils.logutil import RateLimitedReporter
from ..utils import locksan
from .clientset import Clientset


class EventRecorder:
    def __init__(self, clientset: Clientset, component: str,
                 max_cached: int = 4096, buffer: int = 2048):
        self.cs = clientset
        self.component = component
        self._lock = locksan.make_lock("EventRecorder._lock")
        self._seen: Dict[tuple, str] = {}  # aggregation key -> event name
        self._max = max_cached
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer)
        self._worker: threading.Thread = None  # started on first event
        self._drop_reporter = RateLimitedReporter(f"events({component})")

    def event(self, obj, event_type: str, reason: str, message: str):
        """Record an event about obj; repeats bump count instead of piling
        up.  Returns immediately — the API write happens on the sink
        thread."""
        ref = t.ObjectReference(
            # instance lookup, not type(obj).KIND: obj may be a frozen
            # mutsan proxy (informer handout), which forwards per-instance
            kind=obj.KIND,
            namespace=obj.metadata.namespace,
            name=obj.metadata.name,
            uid=obj.metadata.uid,
        )
        try:
            self._q.put_nowait((ref, event_type, reason, message, now_iso()))
        except queue.Full:
            return  # overloaded: drop (best-effort, as upstream)
        self._ensure_worker()

    def flush(self, timeout: float = 5.0):
        """Block until every event enqueued so far has been sent (tests and
        orderly shutdown; upstream's Shutdown analog)."""
        done = threading.Event()
        try:
            self._q.put(done, timeout=timeout)
        except queue.Full:
            return
        self._ensure_worker()
        done.wait(timeout)

    def _ensure_worker(self):
        if self._worker is None:
            with self._lock:
                if self._worker is None:
                    self._worker = threading.Thread(
                        target=self._drain, daemon=True,
                        name=f"event-sink/{self.component}")
                    self._worker.start()

    def _drain(self):
        while True:
            item = self._q.get()
            if isinstance(item, threading.Event):
                item.set()
                continue
            # Coalesce identical items queued behind this one: a burst of N
            # identical events enqueued before the first create completes
            # would each miss _seen (populated only here, after the create)
            # and become N duplicate Event objects.  Collapsing the burst
            # in-queue keeps aggregation semantics identical to the old
            # synchronous path.  Coalescing stops at a flush() fence (so
            # the fence still means "everything enqueued before me was
            # sent") and after at most one buffer's worth of items (so hot
            # producers refilling the queue can't starve sends forever).
            # slot = [first_item, n, latest_ts]: the create keeps the FIRST
            # occurrence's timestamp (when the condition started) while
            # last_timestamp reports the latest repeat, as the synchronous
            # path did.
            batch: Dict[tuple, list] = {}
            batch[self._agg_key(item[0], item[2], item[3])] = \
                [item, 1, item[4]]
            fence = None
            drained = 1
            while fence is None and drained < self._q.maxsize:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if isinstance(nxt, threading.Event):
                    fence = nxt
                    break
                drained += 1
                k = self._agg_key(nxt[0], nxt[2], nxt[3])
                slot = batch.get(k)
                if slot is not None:
                    slot[1] += 1
                    slot[2] = nxt[4]
                else:
                    batch[k] = [nxt, 1, nxt[4]]
            for it, n, last in batch.values():  # dicts keep insertion order
                try:
                    self._send(*it, repeat=n, last_now=last)
                except Exception as e:  # noqa: BLE001 — events are best-effort
                    # rate-limited: during an apiserver outage EVERY batch
                    # entry fails — one summary line per window, not one
                    # line per event, or the flood buries real diagnostics
                    self._drop_reporter.report(f"last {it[2]}: {e}", n=n)
            if fence is not None:
                fence.set()

    @staticmethod
    def _agg_key(ref, reason: str, message: str) -> tuple:
        return (ref.kind, ref.namespace, ref.name, reason, message[:64])

    def _send(self, ref, event_type: str, reason: str, message: str,
              now: str, repeat: int = 1, last_now: str = ""):
        key = self._agg_key(ref, reason, message)
        with self._lock:
            existing = self._seen.get(key)
        ns = ref.namespace or "default"
        if existing:
            self._bump(existing, ns, last_now or now, repeat)
            return
        ev = t.Event()
        ev.metadata.generate_name = f"{ref.name}."
        ev.metadata.namespace = ns
        ev.involved_object = ref
        ev.type = event_type
        ev.reason = reason
        ev.message = message
        ev.source_component = self.component
        ev.first_timestamp = now
        ev.last_timestamp = last_now or now
        if repeat > 1:
            ev.count = repeat
        created = self.cs.events.create(ev, ns)
        with self._lock:
            if len(self._seen) > self._max:
                self._seen.clear()
            self._seen[key] = created.metadata.name

    def _bump(self, name: str, ns: str, now: str, repeat: int = 1):
        ev = self.cs.events.get(name, ns)
        ev.count += repeat
        ev.last_timestamp = now
        self.cs.events.update(ev)
