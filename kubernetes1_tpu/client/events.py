"""Event recorder (ref: client-go tools/record) — best-effort, rate-bounded
event creation with count aggregation for repeats."""

from __future__ import annotations

import threading
from typing import Dict

from ..api import types as t
from ..machinery import now_iso
from .clientset import Clientset


class EventRecorder:
    def __init__(self, clientset: Clientset, component: str, max_cached: int = 4096):
        self.cs = clientset
        self.component = component
        self._lock = threading.Lock()
        self._seen: Dict[tuple, str] = {}  # aggregation key -> event name
        self._max = max_cached

    def event(self, obj, event_type: str, reason: str, message: str):
        """Record an event about obj; repeats bump count instead of piling up."""
        ref = t.ObjectReference(
            kind=type(obj).KIND,
            namespace=obj.metadata.namespace,
            name=obj.metadata.name,
            uid=obj.metadata.uid,
        )
        key = (ref.kind, ref.namespace, ref.name, reason, message[:64])
        now = now_iso()
        with self._lock:
            existing = self._seen.get(key)
        ns = ref.namespace or "default"
        try:
            if existing:
                self._bump(existing, ns, now)
                return
            ev = t.Event()
            ev.metadata.generate_name = f"{ref.name}."
            ev.metadata.namespace = ns
            ev.involved_object = ref
            ev.type = event_type
            ev.reason = reason
            ev.message = message
            ev.source_component = self.component
            ev.first_timestamp = now
            ev.last_timestamp = now
            created = self.cs.events.create(ev, ns)
            with self._lock:
                if len(self._seen) > self._max:
                    self._seen.clear()
                self._seen[key] = created.metadata.name
        except Exception:  # noqa: BLE001 — events are best-effort
            pass

    def _bump(self, name: str, ns: str, now: str):
        ev = self.cs.events.get(name, ns)
        ev.count += 1
        ev.last_timestamp = now
        self.cs.events.update(ev)
