"""Reflector + SharedInformer: the LIST+WATCH cache every control loop uses.

Ref: client-go tools/cache/{reflector.go:239,shared_informer.go,delta_fifo.go}.
Semantics preserved:
- initial LIST seeds the cache and records the collection resourceVersion;
- WATCH resumes from that version so no event is missed (exactly-once
  delivery into the local cache);
- a 410 Expired (compacted revision) triggers full relist — handlers see a
  resync as adds/updates/deletes computed against the existing cache;
- handlers run on a single dispatch thread per informer (ordering guarantee),
  and has_synced() gates controllers until the first LIST is delivered.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from ..api import types as t
from ..machinery import ApiError, TooOldResourceVersion
from ..utils import flightrec, invariants, locksan, mutsan
from ..utils.metrics import Counter, Histogram
from . import retry as _retry
from .clientset import Clientset, ResourceClient

# Fleet-visible informer counters (module-level, the retries_total
# pattern): every informer in the process bumps the labeled family, the
# apiserver renders it on /metrics for in-process components
# (LocalCluster) and remote component processes register it into their
# own /metrics registry (scheduler/controllers __main__) — the
# ObsCollector then sees relists/reconnects with zero bespoke plumbing.
# Each SharedInformer ALSO keeps its own private counter so the
# `relists`/`reconnects` attributes stay per-instance (tests wait on
# THIS informer's recovery, not the process's).
informer_relists_total = Counter(
    "ktpu_informer_relists_total",
    "informer full-LIST fallbacks (initial sync, stream end, 410)")
informer_reconnects_total = Counter(
    "ktpu_informer_reconnects_total",
    "informer mid-stream watch re-dials (resumed from last rv)")
informer_relist_bytes_total = Counter(
    "ktpu_informer_relist_bytes_total",
    "response-body bytes informers paid for full relists — the cost "
    "progress bookmarks exist to amortize away (an idle informer that "
    "keeps relisting shows up here as periodic collection-sized spikes)")

# Default relist chunk size (client-go's reflector pages at 500 too): a
# 150k-pod relist arrives as bounded chunks instead of one giant body —
# the LIST rv stays the FIRST chunk's, so the watch that follows replays
# anything the later chunks raced (idempotent upserts).  0 disables
# pagination (one request, today's wire).
DEFAULT_RELIST_LIMIT = 500

# Watch-lag SLI: delivered-at minus committed-at per group-commit batch,
# labeled by the OWNING SHARD (rev % stride — composite-rv-aware).  The
# stamp rides watch-lag bookmark frames the informer opts into
# (lagStamps); both clocks are CLOCK_MONOTONIC, comparable across
# processes on one host.  Lag is PER-SHARD by construction: a stamp
# names the shard whose commit it times, so no cross-shard clock math
# ever happens.
informer_lag_seconds = Histogram(
    "ktpu_informer_lag_seconds",
    "watch delivery lag (delivered-at minus committed-at) per shard",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0, 10.0, 30.0))


class SharedInformer:
    def __init__(
        self,
        client: ResourceClient,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        resync_period: float = 0.0,
        relist_limit: int = DEFAULT_RELIST_LIMIT,
        progress_bookmarks: bool = True,
    ):
        self.client = client
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        # resync_period > 0: every period, redeliver every cached object
        # to the update handlers LOCALLY (client-go's DeltaFIFO Resync —
        # no API traffic, no relist).  Level-triggered controllers use it
        # as a backstop: a sync whose effect was lost (crashed worker,
        # external drift the watch can't see) gets recomputed within one
        # period.  0 disables (the default — most controllers are fully
        # event-driven).
        self.resync_period = resync_period
        self.relist_limit = max(0, int(relist_limit))
        # progress bookmarks keep an IDLE informer's resume rv at the
        # server's cache head (no 410 relist after quiet minutes);
        # disable only to A/B the pre-bookmark behavior in tests
        self.progress_bookmarks = progress_bookmarks
        self._cache: Dict[str, Any] = {}
        self._lock = locksan.make_rlock("SharedInformer._lock")
        # observability: how often this informer had to fall back to a
        # full LIST (initial sync, watch stream end, 410-eviction
        # recovery), and how often it re-dialed a watch stream without
        # relisting (mid-stream disconnect resumed from the last rv).
        # utils/metrics Counters (migrated from plain ints) so the
        # module-level family and these per-instance views share one
        # implementation; `relists`/`reconnects` stay readable as ints.
        self._relists_ctr = Counter("ktpu_informer_relists_total")
        self._reconnects_ctr = Counter("ktpu_informer_reconnects_total")
        self._relist_bytes_ctr = Counter("ktpu_informer_relist_bytes_total")
        # unified retry policy: capped full-jitter backoff between relist
        # attempts, reset whenever a relist succeeds (client/retry.py)
        self._backoff = _retry.Backoff(base=0.2, factor=2.0, cap=2.0)
        self._handlers: List[Dict[str, Callable]] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resync_thread: Optional[threading.Thread] = None
        # handlers are serialized: the watch loop and the resync loop are
        # different threads, so the single-dispatch-thread ordering
        # guarantee becomes mutual exclusion + per-source order (resyncs
        # interleave BETWEEN events, never inside a handler)
        self._dispatch_lock = locksan.make_lock("SharedInformer._dispatch")
        self._watch_stream = None

    # ----------------------------------------------------------------- api

    def add_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
    ):
        self._handlers.append({"add": on_add, "update": on_update, "delete": on_delete})

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        if self.resync_period > 0 and self._resync_thread is None:
            self._resync_thread = threading.Thread(
                target=self._resync_loop, daemon=True,
                name=f"informer-resync-{self.client.resource}")
            self._resync_thread.start()
        return self

    def stop(self):
        self._stop.set()
        ws = self._watch_stream
        if ws is not None:
            ws.close()

    @property
    def relists(self) -> int:
        """This informer's full-LIST count (int view of the counter —
        kept as an attribute for every existing consumer)."""
        return int(self._relists_ctr.value)

    @property
    def reconnects(self) -> int:
        return int(self._reconnects_ctr.value)

    @property
    def relist_bytes(self) -> int:
        """Response-body bytes this informer's full relists cost."""
        return int(self._relist_bytes_ctr.value)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # ------------------------------------------------------------- store api
    #
    # SNAPSHOT SEMANTICS: get()/list() hand out the informer's cached
    # objects — shared with every other consumer of this informer and
    # replaced (never mutated) on watch updates.  Treat them as immutable
    # snapshots; clone() before mutating.  Under KTPU_MUTSAN the cache
    # holds frozen proxies (utils/mutsan) so a violation raises
    # SharedObjectMutationError at the mutation site; without the
    # sanitizer the rule is enforced statically (ktpulint KTPU008).
    # list() always builds a fresh list object, so iterating a snapshot
    # can never be invalidated by a concurrent resync.

    @staticmethod
    def _key(obj) -> str:
        m = obj.metadata
        return f"{m.namespace}/{m.name}" if m.namespace else m.name

    def _shared(self, obj):
        """Freeze an object entering the shared cache (no-op when the
        sanitizer is off).  The origin names this informer so a mutation
        error points back at the handout."""
        return mutsan.freeze(
            obj, f"SharedInformer[{self.client.resource}] cache")

    def get(self, key: str):
        """The cached object for key — a shared, immutable snapshot."""
        with self._lock:
            return self._cache.get(key)

    def list(self) -> List[Any]:
        """Fresh list of the cached objects (shared, immutable snapshots)."""
        with self._lock:
            return list(self._cache.values())

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._cache.keys())

    # ---------------------------------------------------------------- loops

    @staticmethod
    def _observe_lag(bookmark_meta: Dict[str, Any]):
        """Watch-lag SLI: a lag-stamp bookmark's annotations carry
        ``"<shard>:<monotonic commit ts>"`` tokens for every shard the
        just-delivered batch advanced; lag = now minus that shard's
        stamp.  Per-shard by construction — each token times ONE shard's
        own commit clock, so composite streams never mix shard clocks."""
        stamp = ((bookmark_meta.get("annotations") or {})
                 .get(t.COMMITTED_AT_ANNOTATION))
        if not stamp:
            return
        now = time.monotonic()
        for tok in stamp.split():
            shard, _, ts_s = tok.partition(":")
            try:
                lag = now - float(ts_s)
            except ValueError:
                continue
            informer_lag_seconds.labels(shard=shard).observe(max(0.0, lag))

    def _dispatch(self, kind: str, *args):
        with self._dispatch_lock:
            for h in self._handlers:
                fn = h.get(kind)
                if fn is None:
                    continue
                try:
                    fn(*args)
                except Exception:  # noqa: BLE001 — handler bugs must not kill the informer
                    traceback.print_exc()

    def _resync_loop(self):
        """DeltaFIFO-Resync analog: every resync_period, redeliver every
        cached object to the UPDATE handlers from the local cache — zero
        API traffic (this is NOT a relist; the `relists` counter proves
        it).  old is new on a resync delivery, the client-go convention
        level-triggered handlers rely on to tell a backstop tick from a
        real change without a field diff."""
        while not self._stop.wait(self.resync_period):
            if not self._synced.is_set():
                continue  # nothing cached to redeliver yet
            for obj in self.list():
                if self._stop.is_set():
                    return
                self._dispatch("update", obj, obj)

    def _relist(self) -> str:
        # price the relist in wire bytes: every LIST chunk below runs on
        # THIS thread, so the client's per-thread rx meter deltas cleanly
        # (duck-typed fake clients without a real ApiClient skip the meter)
        api = getattr(self.client, "api", None)
        rx0 = api.rx_bytes() if api is not None else 0
        items, rv = self.client.list(
            namespace=self.namespace,
            label_selector=self.label_selector,
            field_selector=self.field_selector,
            limit=self.relist_limit,
        )
        fresh = {self._key(o): self._shared(o) for o in items}
        with self._lock:
            old = self._cache
            self._cache = fresh
        self._relists_ctr.inc()
        informer_relists_total.labels(resource=self.client.resource).inc()
        rx = (api.rx_bytes() - rx0) if api is not None else 0
        if rx > 0:
            self._relist_bytes_ctr.inc(rx)
            informer_relist_bytes_total.labels(
                resource=self.client.resource).inc(rx)
        flightrec.note("informer", flightrec.INFORMER_RELIST,
                       resource=self.client.resource)
        for key, obj in fresh.items():
            if key in old:
                self._dispatch("update", old[key], obj)
            else:
                self._dispatch("add", obj)
        for key, obj in old.items():
            if key not in fresh:
                self._dispatch("delete", obj)
        self._synced.set()
        return rv

    def _run(self):
        rv = "0"
        while not self._stop.is_set():
            try:
                rv = self._relist()
                self._backoff.reset()
                self._watch_loop(rv)
            except ApiError as e:
                # capped full-jitter backoff, honoring a 429's Retry-After
                # as the floor — a shed informer must not hammer an
                # already-overloaded apiserver in lockstep with its peers
                _retry.note_retry("informer_relist")
                self._stop.wait(max(_retry.retry_after_of(e) or 0.0,
                                    self._backoff.next()))
            except ConnectionError:
                # unreachable/stopping apiserver: the reflector's answer is
                # silent backoff-and-retry (reflector.go relist), not a
                # traceback — this also keeps test teardown logs clean when
                # a server stops before its watchers.  Deliberately ONLY
                # connection errors: other OSErrors (fd exhaustion, …) keep
                # the loud path below.
                _retry.note_retry("informer_relist")
                self._stop.wait(self._backoff.next())
            except Exception:  # noqa: BLE001
                if not self._stop.is_set():
                    traceback.print_exc()
                    self._stop.wait(1.0)

    def _watch_loop(self, rv: str):
        first_stream = True
        dial_failures = 0
        while not self._stop.is_set():
            try:
                stream = self.client.watch(
                    namespace=self.namespace,
                    resource_version=rv,
                    label_selector=self.label_selector,
                    field_selector=self.field_selector,
                    lag_stamps=True,
                    progress_bookmarks=self.progress_bookmarks,
                )
            except TooOldResourceVersion:
                return  # relist
            except ConnectionError:
                # watch DIAL failed (server restarting, injected drop): a
                # few jittered re-dials from the same rv before falling
                # back to the outer relist path — reflector.go re-watches
                # from lastSyncResourceVersion, it does not relist on
                # every blip
                dial_failures += 1
                if dial_failures > 3 or self._stop.is_set():
                    raise
                _retry.note_retry("watch_redial")
                self._stop.wait(self._backoff.next())
                continue
            dial_failures = 0
            if not first_stream:
                # a re-dial after a mid-stream disconnect, resumed from
                # the last delivered rv — no relist needed, no event lost
                self._reconnects_ctr.inc()
                informer_reconnects_total.labels(
                    resource=self.client.resource).inc()
                flightrec.note("informer", flightrec.WATCH_RECONNECT,
                               resource=self.client.resource)
                _retry.note_retry("watch_reconnect")
            first_stream = False
            self._watch_stream = stream
            delivered = False
            # Sharded apiservers interleave shards on one stream, so a
            # single object's rv cannot position the WHOLE stream; they
            # emit BOOKMARK frames carrying the composite resume
            # position instead (after every batch and on heartbeats).
            # The resume point is COMPOSITE-STICKY: once rv is composite
            # (the relist rv or any bookmark), per-object single-int rvs
            # never overwrite it — a stream cut between an event and its
            # bookmark would otherwise resume from ONE shard's revision
            # and silently gap every other shard (resuming from the last
            # composite merely re-delivers events, which the cache
            # upserts idempotently).  Plain streams never mint
            # composites: behavior unchanged.
            try:
                for ev_type, obj_dict in stream:
                    delivered = True
                    if self._stop.is_set():
                        return
                    if ev_type == "BOOKMARK":
                        meta = obj_dict.get("metadata") or {}
                        rv = meta.get("resourceVersion") or rv
                        self._observe_lag(meta)
                        continue
                    obj = self._shared(self.client.scheme.decode(obj_dict))
                    prev_rv = rv
                    if "." not in str(rv):
                        rv = obj.metadata.resource_version or rv
                    # probe: the composite-sticky rule — a sharded
                    # ("shard.counter") resume point must never regress
                    # to a bare per-object revision (resuming there
                    # replays or skips whole shards)
                    invariants.composite_sticky("informer.resume",
                                                prev_rv, rv)
                    key = self._key(obj)
                    if ev_type == "DELETED":
                        with self._lock:
                            old = self._cache.pop(key, None)
                        self._dispatch("delete", obj if old is None else old)
                    elif ev_type in ("ADDED", "MODIFIED"):
                        with self._lock:
                            old = self._cache.get(key)
                            self._cache[key] = obj
                        if old is None:
                            self._dispatch("add", obj)
                        else:
                            self._dispatch("update", old, obj)
                    elif ev_type == "ERROR":
                        status = obj_dict
                        if status.get("code") == 410:
                            return  # relist
            finally:
                self._watch_stream = None
                stream.close()
            # stream ended — server timeout/restart, or a mid-frame cut
            # (WatchStream.__iter__ absorbs connection errors and ends
            # the iteration): every event up to the cut was delivered
            # and applied, so re-watch from the last delivered rv; the
            # outer loop's relist is only for a compacted rv (410).
            if delivered:
                self._backoff.reset()  # productive stream: blip starts small
            else:
                # the server ACCEPTED the dial then ended the stream with
                # nothing on it (cacher reseeding mid-failover, an LB
                # accepting-then-closing): re-dialing at full speed
                # hammers exactly the server that is struggling — treat
                # it like a failed dial and back off (reflector.go backs
                # off between watch attempts for the same reason)
                self._stop.wait(self._backoff.next())


class InformerFactory:
    """Shared informers per resource (ref: informers.SharedInformerFactory)."""

    def __init__(self, clientset: Clientset):
        self.clientset = clientset
        self._informers: Dict[tuple, SharedInformer] = {}
        self._lock = locksan.make_lock("InformerFactory._lock")

    def informer(
        self,
        resource: str,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        resync_period: float = 0.0,
    ) -> SharedInformer:
        """resync_period > 0 asks the SHARED informer for a periodic
        local resync (SharedInformer.resync_period).  Consumers of one
        shared informer may ask for different periods: the shortest
        non-zero ask wins (client-go's AddEventHandlerWithResyncPeriod
        rule) — a faster backstop satisfies every slower one."""
        key = (resource, namespace, label_selector, field_selector)
        with self._lock:
            inf = self._informers.get(key)
            if inf is None:
                inf = self._informers[key] = SharedInformer(
                    self.clientset.resource(resource),
                    namespace=namespace,
                    label_selector=label_selector,
                    field_selector=field_selector,
                    resync_period=resync_period,
                )
            elif resync_period > 0 and (inf.resync_period == 0
                                        or resync_period < inf.resync_period):
                if inf._thread is not None:
                    # started informers can't honor a new ask: a 0-period
                    # informer never spawned a resync thread, so silently
                    # recording the period would promise a backstop that
                    # never fires
                    raise ValueError(
                        f"informer {key} already started with "
                        f"resync_period={inf.resync_period}; ask before "
                        f"start_all() so the shortest period can win")
                inf.resync_period = resync_period
            return inf

    def start_all(self):
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        with self._lock:
            informers = list(self._informers.values())
        return all(inf.wait_for_sync(timeout) for inf in informers)

    def stop_all(self):
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()
