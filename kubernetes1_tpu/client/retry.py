"""Conflict-retry helper for read-modify-write loops.

Ref: client-go staging/src/k8s.io/client-go/util/retry/util.go (RetryOnConflict,
DefaultRetry backoff). Any client that does get → mutate → update races with
controllers updating the same object's status; the idiomatic answer is to retry
the whole read-modify-write on a 409 with a short backoff.
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

from ..machinery.errors import Conflict

T = TypeVar("T")

# Mirrors client-go's DefaultRetry: 5 steps, 10ms base, factor 1.0 + jitter.
DEFAULT_STEPS = 5
DEFAULT_SLEEP = 0.01


def retry_on_conflict(
    fn: Callable[[], T],
    steps: int = DEFAULT_STEPS,
    sleep: float = DEFAULT_SLEEP,
) -> T:
    """Run fn (a full read-modify-write closure) retrying on Conflict.

    fn must re-GET the object on each attempt; retrying a stale in-memory
    object would conflict forever.
    """
    last: Conflict
    for i in range(steps):
        try:
            return fn()
        except Conflict as e:
            last = e
            time.sleep(sleep * (i + 1))
    raise last
