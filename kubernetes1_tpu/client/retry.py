"""Unified client retry policy: backoff, jitter, and error classification.

Ref: client-go staging/src/k8s.io/client-go/util/retry/util.go
(RetryOnConflict) + util/flowcontrol's backoff managers, and the AWS
"exponential backoff and jitter" shape (full jitter: sleep ~ U(0, cap)).
One policy, shared by every client-side loop that talks to an apiserver —
the REST transport (client/rest.py), informer watch reconnects, the
scheduler's bind fallback, and the kubelet's status sync — so the answers
to "which errors retry, and with what backoff" cannot drift per caller:

- TRANSIENT (retry): connection-level failures (incl. injected faults —
  utils/faultline raises a ConnectionError subclass), HTTP 429 overload
  sheds, and 5xx server errors.  A 429's ``Retry-After`` is honored as a
  FLOOR under the jittered backoff.
- TERMINAL (surface to the caller): everything else — 4xx semantics
  (Conflict has its own loop below, NotFound/Forbidden mean what they
  say), and 410 Expired, whose answer is a relist, not a retry.

Jitter is FULL jitter from a seeded stream when a faultline schedule is
active, so chaos runs replay their sleeps too.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, Optional, TypeVar

from ..machinery.errors import ApiError, Conflict, TooOldResourceVersion
from ..utils import faultline
from ..utils.metrics import Counter

T = TypeVar("T")

# Mirrors client-go's DefaultRetry: 5 steps from a 10ms base.
DEFAULT_STEPS = 5
DEFAULT_SLEEP = 0.01

# HTTP codes that mean "the server (or the path to it) is momentarily
# unhappy, the request semantics are fine": overload shed + server errors.
TRANSIENT_CODES = frozenset({429, 500, 502, 503, 504})

# Every retry any client takes, by reason — scraped into bench.py's
# density JSON and rendered on the apiserver's /metrics (same process for
# LocalCluster; remote components export it from their own /metrics).
retries_total = Counter(
    "ktpu_client_retries_total", "client retries by reason")


def note_retry(reason: str) -> None:
    retries_total.labels(reason=reason).inc()


def retries_snapshot() -> Dict[str, int]:
    """{reason: count} across every labeled child (bench.py's scrape)."""
    out: Dict[str, int] = {}
    for child in retries_total._children_snapshot():
        for k, v in (child._labels or ()):
            if k == "reason":
                out[v] = int(child.value)
    return out


def retries_delta(before: Dict[str, int]) -> Dict[str, int]:
    """Nonzero {reason: count} growth since a retries_snapshot() —
    retries_total is process-cumulative, so per-phase reporters
    (bench.py, scripts/chaos.py) diff against their entry snapshot."""
    now = retries_snapshot()
    return {k: v - before.get(k, 0) for k, v in now.items()
            if v - before.get(k, 0)}


def is_transient(exc: BaseException) -> bool:
    """Transient-vs-terminal classification (see module docstring)."""
    if isinstance(exc, TooOldResourceVersion):
        return False  # 410: relist, don't retry
    if isinstance(exc, Conflict):
        return False  # 409: re-GET then retry — retry_on_conflict's job
    if isinstance(exc, ApiError):
        return getattr(exc, "code", 500) in TRANSIENT_CODES
    # connection-level failures, incl. faultline's FaultInjected
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


def retry_after_of(exc: BaseException) -> Optional[float]:
    """The server-requested wait (seconds) carried by a 429/503 response
    (client/rest.py stamps it from the Retry-After header)."""
    ra = getattr(exc, "retry_after", None)
    try:
        return float(ra) if ra is not None else None
    except (TypeError, ValueError):
        return None


class Backoff:
    """Capped exponential backoff with FULL jitter: attempt n sleeps
    ~ U(0, min(cap, base * factor**n)).  Full jitter (vs the +/-10%
    decorrelation client-go uses) is what de-synchronizes a thundering
    herd of identical clients after a shared failure — the exact shape a
    shed-and-retry storm has."""

    def __init__(self, base: float = 0.02, factor: float = 2.0,
                 cap: float = 1.0, rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.cap = cap
        self._rng = rng
        self._n = 0

    def _random(self) -> random.Random:
        # seeded stream under an active faultline schedule → deterministic
        # chaos; the process-global stream otherwise
        return self._rng or faultline.rng() or random  # type: ignore[return-value]

    def ceiling(self) -> float:
        return min(self.cap, self.base * self.factor ** self._n)

    def next(self) -> float:
        d = self._random().uniform(0.0, self.ceiling())
        self._n += 1
        return d

    def reset(self) -> None:
        self._n = 0

    def sleep(self, floor: float = 0.0) -> None:
        """One jittered backoff sleep; `floor` (a server's Retry-After) is
        honored as a minimum."""
        time.sleep(max(floor, self.next()))


def retry_on_conflict(
    fn: Callable[[], T],
    steps: int = DEFAULT_STEPS,
    sleep: float = DEFAULT_SLEEP,
) -> T:
    """Run fn (a full read-modify-write closure) retrying on Conflict,
    with capped-exponential full-jitter backoff between attempts.

    fn must re-GET the object on each attempt; retrying a stale in-memory
    object would conflict forever.
    """
    backoff = Backoff(base=sleep, factor=2.0, cap=sleep * 16)
    last: Conflict
    for i in range(steps):
        try:
            return fn()
        except Conflict as e:
            last = e
            if i < steps - 1:
                note_retry("conflict")
                backoff.sleep()
    raise last


def call_with_retries(
    fn: Callable[[], T],
    steps: int = 4,
    backoff: Optional[Backoff] = None,
    reason: str = "transient",
    classify: Callable[[BaseException], bool] = is_transient,
) -> T:
    """Run fn retrying TRANSIENT failures (per `classify`) with jittered
    backoff, honoring any Retry-After the failure carries as a sleep
    floor.  Terminal errors — and the last attempt's — surface as-is."""
    bo = backoff or Backoff()
    for i in range(steps):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified right below; terminal re-raises
            if i == steps - 1 or not classify(e):
                raise
            note_retry(reason)
            bo.sleep(floor=retry_after_of(e) or 0.0)
    raise AssertionError("unreachable")  # pragma: no cover
