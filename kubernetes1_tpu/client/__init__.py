from .rest import ApiClient
from .clientset import Clientset, ResourceClient
from .informer import SharedInformer, InformerFactory
from .leaderelection import LeaderElector, LeaseSet
from .events import EventRecorder
from .retry import retry_on_conflict
