"""HTTP REST client with streaming watch (ref: client-go rest + dynamic).

Connections are pooled per thread for request/response calls; every watch
gets a dedicated connection whose chunked body is consumed line by line —
each non-empty line is one {"type","object"} frame (heartbeat lines are
blank).  Errors arrive as Status objects and are re-raised as the typed
ApiError hierarchy so callers can distinguish Conflict/NotFound/Expired.
"""

from __future__ import annotations

import http.client
import json
import socket
import ssl
import threading
from typing import Any, Dict, Iterator, Optional, Tuple
from urllib.parse import urlencode, urlparse

from ..utils import fasthttp, faultline, spans

from ..machinery import ApiError
from . import retry as _retry

# How many times a request that was shed (HTTP 429 carrying Retry-After)
# is transparently re-submitted after honoring the server's wait.  A shed
# is refused BEFORE dispatch, so re-sending is safe even for mutations.
SHED_RETRIES = 2


def _parse_retry_after(resp) -> Optional[float]:
    """Seconds from a Retry-After header, or None.  Fractional values are
    accepted (the ktpu apiserver sheds with sub-second waits)."""
    v = resp.getheader("Retry-After")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def client_ssl_context(
    ca_file: str = "",
    cert_file: str = "",
    key_file: str = "",
    insecure: bool = False,
) -> ssl.SSLContext:
    """TLS context for talking to a ktpu server: verify the cluster CA,
    present a client certificate when given (the x509 authn channel —
    CN=user, O=groups)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if insecure:
        # EXPLICIT opt-out only (join-time discovery connects unverified
        # once, pins the CA hash, then reconnects verified — kubeadm token
        # discovery shape).  No ca_file is NOT an implicit opt-out: that
        # would silently hand bearer tokens to any MITM.
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif ca_file:
        ctx.load_verify_locations(cafile=ca_file)
    else:
        ctx.load_default_certs(ssl.Purpose.SERVER_AUTH)
    if cert_file:
        ctx.load_cert_chain(certfile=cert_file, keyfile=key_file or None)
    return ctx


class WatchStream:
    """Iterator over (event_type, obj_dict); close() to abort."""

    def __init__(self, conn: http.client.HTTPConnection, resp: http.client.HTTPResponse):
        self._conn = conn
        self._resp = resp
        self._closed = False

    def __iter__(self) -> Iterator[Tuple[str, Dict[str, Any]]]:
        try:
            while not self._closed:
                # fault injection: an injected drop/sever here ends the
                # stream exactly like a mid-frame connection cut — the
                # consumer (informer) must reconnect/relist losslessly
                faultline.check("client.watch")
                line = self._resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue  # heartbeat
                frame = json.loads(line)
                yield frame["type"], frame["object"]
        except (
            http.client.IncompleteRead,
            ConnectionResetError,
            OSError,
            ValueError,
            AttributeError,  # fp=None race when close() lands mid-readline
        ):
            return

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        self._closed = True
        try:
            # wake a reader blocked in recv(); bare close() leaves it blocked
            # until the server's next heartbeat
            sock = self._conn.sock  # snapshot: concurrent close() may null it
            if sock is not None:
                sock.shutdown(socket.SHUT_RDWR)
        except (OSError, AttributeError):
            pass
        try:
            self._conn.close()
        except OSError:
            pass


class ApiClient:
    """REST client; `url` may be a comma-separated server list — on a
    connection failure the client fails over to the next server (HA
    apiservers are stateless peers over one store, so any of them serves;
    the reference's client-go takes the same server list via kubeconfig)."""

    def __init__(self, url: str, token: str = "", timeout: float = 30.0,
                 ca_file: str = "", cert_file: str = "", key_file: str = "",
                 insecure: bool = False):
        # fast header parsing for every component built on this client;
        # installed at construction, not import (utils/fasthttp.py)
        fasthttp.install()
        self.urls = [u.strip().rstrip("/") for u in url.split(",")
                     if u.strip()]
        schemes = {urlparse(u).scheme for u in self.urls}
        if len(schemes) > 1:
            raise ValueError(
                f"server list mixes schemes {sorted(schemes)}: every HA "
                f"peer must be dialed the same way ({url!r})")
        self.tls = schemes == {"https"}
        self._servers = [
            (p.hostname or "127.0.0.1",
             p.port or (443 if self.tls else 80))
            for p in map(urlparse, self.urls)
        ]
        self._active = 0
        self.token = token
        self.timeout = timeout
        self.ssl_context: Optional[ssl.SSLContext] = (
            client_ssl_context(ca_file, cert_file, key_file, insecure)
            if self.tls else None
        )
        self._local = threading.local()

    @property
    def url(self) -> str:
        return self.urls[self._active]

    @property
    def host(self) -> str:
        return self._servers[self._active][0]

    @property
    def port(self) -> int:
        return self._servers[self._active][1]

    def _rotate(self, from_idx: int):
        """Advance to the next server (no-op if another thread already
        did); per-thread pooled connections notice via the index stamp."""
        if len(self._servers) > 1 and self._active == from_idx:
            self._active = (from_idx + 1) % len(self._servers)

    # ------------------------------------------------------------- plumbing

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json", "Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        # request tracing (utils/spans): propagate the caller's active span
        # context, or mint a fresh root so every request is correlatable —
        # the server side stamps the id into created objects' metadata
        h[spans.HEADER] = spans.inject_header()
        return h

    def _new_conn(self, timeout) -> http.client.HTTPConnection:
        faultline.check("client.dial")
        host, port = self._servers[self._active]
        if self.tls:
            conn = http.client.HTTPSConnection(
                host, port, timeout=timeout, context=self.ssl_context)
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        # request body goes out in a separate send from the headers; without
        # NODELAY, Nagle can hold the second segment behind a delayed ACK
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except (OSError, AttributeError):
            pass
        return conn

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is not None and getattr(self._local, "idx", -1) != self._active:
            self._reset_conn()  # failed over: stale server's socket
            conn = None
        if conn is None:
            conn = self._new_conn(self.timeout)
            self._local.conn = conn
            self._local.idx = self._active
        return conn

    def _reset_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def request(
        self,
        method: str,
        path: str,
        body: Optional[Any] = None,
        params: Optional[Dict[str, str]] = None,
        raw: bool = False,
        content_type: str = "",
    ) -> Any:
        """JSON round-trip by default; raw=True returns the response bytes
        verbatim (non-JSON subresources like pods/<name>/log).

        `body` may be PRE-ENCODED bytes — the hot bind path splices
        per-item serialized bytes into one envelope (or ships a whole
        codec payload) instead of re-walking a dict tree through
        json.dumps per request; `content_type` overrides the JSON
        default for such bodies (e.g. application/x-ktpu-pybin1)."""
        if params:
            path = path + "?" + urlencode({k: v for k, v in params.items() if v != ""})
        if isinstance(body, (bytes, bytearray)):
            payload = bytes(body)
        else:
            payload = json.dumps(body).encode() if body is not None else None
        headers = self._headers()
        if content_type:
            headers["Content-Type"] = content_type
        # Retry rules (the unified client/retry policy): GET retries on any
        # connection error; mutations retry only when the failure happened
        # while *sending* (stale keep-alive connection — the server never
        # saw the request).  A mutation whose response was lost may have
        # been applied, so re-sending it could duplicate the action.  Each
        # connection-level failure also fails over to the next server in
        # the list (HA apiservers), with capped full-jitter backoff
        # between redials.  An HTTP 429 that carries Retry-After is an
        # overload SHED — refused before dispatch — so it is re-submitted
        # (mutations included) after honoring the server's wait; a 429
        # without the header (e.g. a PDB eviction denial) is a real answer
        # and surfaces immediately.
        attempts = 1 + max(1, len(self._servers))
        if method == "GET":
            # idempotent: a deeper redial budget (jitter-backed) — a
            # couple of dropped frames must not fail a read that any
            # retry would serve; mutations keep the strict
            # may-have-been-applied rules above
            attempts = max(4, attempts)
        backoff = _retry.Backoff(base=0.02, cap=0.5)
        retry_after: Optional[float] = None
        for shed_attempt in range(SHED_RETRIES + 1):
            for attempt in range(attempts):
                idx = self._active
                sent = False
                try:
                    conn = self._conn()
                    faultline.check("client.request")
                    conn.request(method, path, body=payload,
                                 headers=headers)
                    sent = True
                    resp = conn.getresponse()
                    raw_body = resp.read()
                    # per-thread response-byte meter (relist-bytes SLI:
                    # the informer deltas this around a LIST to price a
                    # full relist in wire bytes).  Thread-local — zero
                    # contention on the request hot path.
                    self._local.rx_bytes = (
                        getattr(self._local, "rx_bytes", 0) + len(raw_body))
                    break
                except (http.client.HTTPException, ConnectionError, OSError):
                    self._reset_conn()
                    self._rotate(idx)
                    if attempt == attempts - 1 or (sent and method != "GET"):
                        raise
                    _retry.note_retry("transport")
                    backoff.sleep()
            retry_after = _parse_retry_after(resp)
            if (resp.status == 429 and retry_after is not None
                    and shed_attempt < SHED_RETRIES):
                _retry.note_retry("shed")
                backoff.sleep(floor=min(retry_after, 2.0))
                continue
            break
        if raw and resp.status < 400:
            return raw_body
        try:
            data = json.loads(raw_body) if raw_body else {}
        except ValueError:
            data = {}
        if resp.status >= 400:
            if data.get("kind") == "Status":
                err = ApiError.from_status(data)
            else:
                err = ApiError(f"{method} {path}: HTTP {resp.status}")
                err.code = resp.status
            if retry_after is not None:
                # callers (informers, controllers) honor this as a
                # backoff floor — see client/retry.retry_after_of
                err.retry_after = retry_after
            raise err
        return data

    def upgrade(self, path: str, proto: str,
                timeout: float = 30.0) -> socket.socket:
        """Perform an HTTP Upgrade handshake against the active server and
        return the raw socket (the persistent bind-stream leg rides this).
        Connection-level failures rotate through the HA server list like
        request(); an UpgradeRefused (the server is alive but answered a
        real status — an older apiserver's 404) surfaces to the caller
        undisturbed so it can stick to its fallback path."""
        from ..utils import streams as _streams

        headers = {k: v for k, v in self._headers().items()
                   if k not in ("Content-Type", "Accept")}
        backoff = _retry.Backoff(base=0.02, cap=0.5)
        attempts = max(1, len(self._servers))
        for attempt in range(attempts):
            idx = self._active
            host, port = self._servers[idx]
            try:
                return _streams.upgrade_request(
                    host, port, path, headers, timeout=timeout,
                    ssl_context=self.ssl_context, proto=proto)
            except _streams.UpgradeRefused:
                raise  # a live server's real answer: no failover
            except (ConnectionError, OSError):
                self._rotate(idx)
                if attempt == attempts - 1:
                    raise
                _retry.note_retry("transport")
                backoff.sleep()
        raise ConnectionError(f"upgrade {path}: no server reachable")

    def watch(
        self, path: str, params: Optional[Dict[str, str]] = None
    ) -> WatchStream:
        params = dict(params or {})
        params["watch"] = "true"
        full = path + "?" + urlencode({k: v for k, v in params.items() if v != ""})
        last_exc: Optional[Exception] = None
        conn = None
        backoff = _retry.Backoff(base=0.02, cap=0.5)
        dials = max(1, len(self._servers))
        for dial in range(dials):
            idx = self._active
            try:
                faultline.check("client.watch")
                conn = self._new_conn(None)
                conn.request("GET", full, headers=self._headers())
                resp = conn.getresponse()
                break
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._rotate(idx)
                last_exc = e
                if dial < dials - 1:
                    _retry.note_retry("watch_dial")
                    backoff.sleep()
        else:
            raise last_exc  # every server refused the watch dial
        if resp.status >= 400:
            raw = resp.read()
            conn.close()
            data = json.loads(raw) if raw else {}
            if data.get("kind") == "Status":
                raise ApiError.from_status(data)
            err = ApiError(f"watch {path}: HTTP {resp.status}")
            err.code = resp.status
            raise err
        return WatchStream(conn, resp)

    def rx_bytes(self) -> int:
        """Cumulative response-body bytes received on THIS thread (watch
        streams excluded — they bypass request()).  Callers meter a
        specific operation by deltaing around it on its own thread."""
        return getattr(self._local, "rx_bytes", 0)

    def close(self):
        self._reset_conn()
