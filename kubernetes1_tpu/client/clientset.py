"""Typed clientset over ApiClient (ref: client-go kubernetes.Clientset).

Each ResourceClient handles one resource's full verb set including the
status and binding subresources; objects cross the wire as scheme-encoded
JSON and come back as typed dataclasses.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..api import types as t
from ..machinery import TooOldResourceVersion
from ..machinery.scheme import Scheme, global_scheme
from .rest import ApiClient, WatchStream

_GROUP_PATH = {
    "jobs": "/apis/batch/v1",
    "cronjobs": "/apis/batch/v1",
    "replicasets": "/apis/apps/v1",
    "deployments": "/apis/apps/v1",
    "daemonsets": "/apis/apps/v1",
    "statefulsets": "/apis/apps/v1",
    "priorityclasses": "/apis/scheduling/v1",
    "horizontalpodautoscalers": "/apis/autoscaling/v1",
    "poddisruptionbudgets": "/apis/policy/v1",
    "certificatesigningrequests": "/apis/certificates/v1",
    "customresourcedefinitions": "/apis/apiextensions/v1",
    "apiservices": "/apis/apiregistration/v1",
    "podmetrics": "/apis/metrics.k8s.io/v1",
    "nodemetrics": "/apis/metrics.k8s.io/v1",
    "podcustommetrics": "/apis/custom.metrics.k8s.io/v1",
    "roles": "/apis/rbac/v1",
    "clusterroles": "/apis/rbac/v1",
    "rolebindings": "/apis/rbac/v1",
    "clusterrolebindings": "/apis/rbac/v1",
}


class ResourceClient:
    def __init__(self, api: ApiClient, resource: str, scheme: Scheme):
        self.api = api
        self.resource = resource
        self.scheme = scheme
        self.namespaced = scheme.namespaced.get(resource, True)
        self._base = _GROUP_PATH.get(resource, "/api/v1")

    def _path(self, namespace: str = "", name: str = "", sub: str = "") -> str:
        parts = [self._base]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.resource)
        if name:
            parts.append(name)
        if sub:
            parts.append(sub)
        return "/".join(parts)

    # ---------------------------------------------------------------- verbs

    def create(self, obj, namespace: str = ""):
        ns = namespace or obj.metadata.namespace or ("default" if self.namespaced else "")
        data = self.api.request("POST", self._path(ns), body=self.scheme.encode(obj))
        return self.scheme.decode(data)

    def get(self, name: str, namespace: str = "default"):
        data = self.api.request("GET", self._path(namespace, name))
        return self.scheme.decode(data)

    def list(
        self,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        limit: int = 0,
    ) -> Tuple[List[Any], str]:
        """limit=0 (default): one request, the whole collection — the
        wire today.  limit>0: paginated — continue tokens are followed
        until the collection is exhausted and the returned rv is the
        FIRST chunk's: a watch resumed there replays every event the
        later chunks raced, so list+watch stays lossless (re-deliveries
        upsert idempotently).  A stale token (410 — the anchor revision
        aged out of the server's watch window mid-pagination) restarts
        the pagination from scratch; if tokens keep going stale the last
        resort is one unpaginated request, which cannot go stale."""
        if not limit:
            items, rv, _cont = self.list_page(
                namespace, label_selector=label_selector,
                field_selector=field_selector)
            return items, rv
        for _attempt in range(3):
            try:
                return self._list_paged(namespace, label_selector,
                                        field_selector, limit)
            except TooOldResourceVersion:
                continue  # stale continue token: clean restart
        items, rv, _cont = self.list_page(
            namespace, label_selector=label_selector,
            field_selector=field_selector)
        return items, rv

    def _list_paged(self, namespace, label_selector, field_selector,
                    limit) -> Tuple[List[Any], str]:
        items: List[Any] = []
        first_rv = ""
        cont = ""
        while True:
            page, rv, cont = self.list_page(
                namespace, label_selector=label_selector,
                field_selector=field_selector, limit=limit,
                continue_token=cont)
            items.extend(page)
            if not first_rv:
                first_rv = rv
            if not cont:
                return items, first_rv

    def list_page(
        self,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        limit: int = 0,
        continue_token: str = "",
    ) -> Tuple[List[Any], str, str]:
        """One LIST chunk: (items, rv, continue_token) — empty token
        means the collection is exhausted.  Raises TooOldResourceVersion
        (410) when a presented token went stale; servers without
        pagination ignore the params and answer everything with no
        token, so a paginating client degrades to one big chunk."""
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        if limit:
            params["limit"] = str(int(limit))
        if continue_token:
            params["continue"] = continue_token
        data = self.api.request("GET", self._path(namespace), params=params)
        items = [self.scheme.decode(d) for d in data.get("items", [])]
        meta = data.get("metadata") or {}
        return (items, meta.get("resourceVersion", "0"),
                meta.get("continue", ""))  # ktpulint: ignore[KTPU009] ListMeta wire shape — list envelopes carry continue/resourceVersion, no registered dataclass models them

    def update(self, obj):
        ns = obj.metadata.namespace
        data = self.api.request(
            "PUT", self._path(ns, obj.metadata.name), body=self.scheme.encode(obj)
        )
        return self.scheme.decode(data)

    def update_status(self, obj):
        ns = obj.metadata.namespace
        data = self.api.request(
            "PUT",
            self._path(ns, obj.metadata.name, "status"),
            body=self.scheme.encode(obj),
        )
        return self.scheme.decode(data)

    def patch(self, name: str, patch: Dict[str, Any], namespace: str = "default"):
        data = self.api.request("PATCH", self._path(namespace, name), body=patch)
        return self.scheme.decode(data)

    def delete(self, name: str, namespace: str = "default", grace_seconds: Optional[int] = None):
        params = {}
        if grace_seconds is not None:
            params["gracePeriodSeconds"] = str(grace_seconds)
        data = self.api.request("DELETE", self._path(namespace, name), params=params)
        return self.scheme.decode(data)

    def watch(
        self,
        namespace: str = "",
        resource_version: str = "0",
        label_selector: str = "",
        field_selector: str = "",
        timeout_seconds: float = 0,
        lag_stamps: bool = False,
        progress_bookmarks: bool = False,
    ) -> WatchStream:
        params = {"resourceVersion": resource_version}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        if timeout_seconds:
            params["timeoutSeconds"] = str(timeout_seconds)
        if lag_stamps:
            # watch-lag SLI opt-in: the apiserver appends lag-stamp
            # BOOKMARK frames (committed-at annotations) after every
            # delivered batch; old servers ignore the param, so plain
            # streams stay byte-identical for everyone who didn't ask
            params["lagStamps"] = "1"
        if progress_bookmarks:
            # idle-freshness opt-in (informers set it): plain streams get
            # a progress BOOKMARK on heartbeats so an idle watcher's
            # resume rv rides the cache head instead of aging below the
            # compaction floor into a 410 full relist.  Old servers
            # ignore the param; non-opt-in streams stay byte-identical.
            params["progressBookmarks"] = "1"
        return self.api.watch(self._path(namespace), params)


class Clientset:
    def __init__(self, url: str, token: str = "", scheme: Optional[Scheme] = None,
                 ca_file: str = "", cert_file: str = "", key_file: str = "",
                 insecure: bool = False, bind_codec: str = "json",
                 bind_stream: bool = False):
        self.api = ApiClient(url, token=token, ca_file=ca_file,
                             cert_file=cert_file, key_file=key_file,
                             insecure=insecure)
        self.scheme = scheme or global_scheme
        self._clients: Dict[str, ResourceClient] = {}
        # bindings:batch body codec (--bind-codec): "pybin1" ships the
        # bulk-bind envelope as one codec payload (pickle-5 of plain
        # data, decoded by the server's restricted unpickler) instead of
        # a json.dumps walk per request — the scheduler→apiserver hot
        # bind leg's analog of the store wire's negotiated binary
        # framing.  Falls back to JSON once (and stays there) if the
        # server doesn't speak it (400/415 — an older apiserver).
        if bind_codec != "json":
            from ..machinery.codec import get_codec

            get_codec(bind_codec)  # typo'd codec fails at construction
        self.bind_codec = bind_codec
        self._bind_codec_ok = True
        # persistent zero-copy bind leg (--bind-stream): bulk binds ride
        # length-prefixed frames over one upgraded connection per bind
        # worker instead of full HTTP per round; ANY stream failure falls
        # back to the per-request path below for that batch
        # (client/bindstream.py owns the contract)
        self._bind_stream = None
        if bind_stream:
            self.enable_bind_stream()

    def enable_bind_stream(self):
        """Turn on the persistent bind-stream fast path (idempotent;
        uses the clientset's bind_codec for the frame payloads)."""
        if self._bind_stream is None:
            from .bindstream import BindStream

            self._bind_stream = BindStream(self.api, codec_id=self.bind_codec)
        return self._bind_stream

    def prefers_bulk_bind(self) -> bool:
        """True when even a SINGLE bind is cheaper through bind_batch —
        i.e. the persistent bind stream is live (one frame beats one
        HTTP round-trip; the scheduler's bind loop asks this so the
        steady-state trickle rides the zero-copy leg too, not just
        bursts)."""
        return self._bind_stream is not None \
            and not self._bind_stream.unsupported

    @classmethod
    def from_config(cls, path: str, scheme: Optional[Scheme] = None) -> "Clientset":
        """Build from a ktpu config file — the kubeconfig analog written by
        `ktpu init`/`join`: JSON {"server", "token"?, "ca"?, "cert"?, "key"?}
        with cert paths relative to the config file's directory."""
        import json as _json
        import os as _os

        with open(path) as f:
            cfg = _json.load(f)
        base = _os.path.dirname(_os.path.abspath(path))
        rel = lambda p: (p if not p or _os.path.isabs(p)  # noqa: E731
                         else _os.path.join(base, p))
        return cls(cfg["server"], token=cfg.get("token", ""), scheme=scheme,
                   ca_file=rel(cfg.get("ca", "")),
                   cert_file=rel(cfg.get("cert", "")),
                   key_file=rel(cfg.get("key", "")))

    def resource(self, plural: str) -> ResourceClient:
        if plural not in self._clients:
            self._clients[plural] = ResourceClient(self.api, plural, self.scheme)
        return self._clients[plural]

    @property
    def pods(self) -> ResourceClient:
        return self.resource("pods")

    @property
    def nodes(self) -> ResourceClient:
        return self.resource("nodes")

    @property
    def namespaces(self) -> ResourceClient:
        return self.resource("namespaces")

    @property
    def events(self) -> ResourceClient:
        return self.resource("events")

    @property
    def jobs(self) -> ResourceClient:
        return self.resource("jobs")

    @property
    def replicasets(self) -> ResourceClient:
        return self.resource("replicasets")

    @property
    def deployments(self) -> ResourceClient:
        return self.resource("deployments")

    @property
    def daemonsets(self) -> ResourceClient:
        return self.resource("daemonsets")

    @property
    def statefulsets(self) -> ResourceClient:
        return self.resource("statefulsets")

    @property
    def cronjobs(self) -> ResourceClient:
        return self.resource("cronjobs")

    @property
    def services(self) -> ResourceClient:
        return self.resource("services")

    @property
    def endpoints(self) -> ResourceClient:
        return self.resource("endpoints")

    @property
    def leases(self) -> ResourceClient:
        return self.resource("leases")

    @property
    def configmaps(self) -> ResourceClient:
        return self.resource("configmaps")

    @property
    def priorityclasses(self) -> ResourceClient:
        return self.resource("priorityclasses")

    @property
    def secrets(self) -> ResourceClient:
        return self.resource("secrets")

    @property
    def serviceaccounts(self) -> ResourceClient:
        return self.resource("serviceaccounts")

    @property
    def resourcequotas(self) -> ResourceClient:
        return self.resource("resourcequotas")

    @property
    def limitranges(self) -> ResourceClient:
        return self.resource("limitranges")

    @property
    def horizontalpodautoscalers(self) -> ResourceClient:
        return self.resource("horizontalpodautoscalers")

    @property
    def poddisruptionbudgets(self) -> ResourceClient:
        return self.resource("poddisruptionbudgets")

    @property
    def persistentvolumes(self) -> ResourceClient:
        return self.resource("persistentvolumes")

    @property
    def persistentvolumeclaims(self) -> ResourceClient:
        return self.resource("persistentvolumeclaims")

    @property
    def certificatesigningrequests(self) -> ResourceClient:
        return self.resource("certificatesigningrequests")

    @property
    def customresourcedefinitions(self) -> ResourceClient:
        return self.resource("customresourcedefinitions")

    @property
    def apiservices(self) -> ResourceClient:
        return self.resource("apiservices")

    @property
    def roles(self) -> ResourceClient:
        return self.resource("roles")

    @property
    def clusterroles(self) -> ResourceClient:
        return self.resource("clusterroles")

    @property
    def rolebindings(self) -> ResourceClient:
        return self.resource("rolebindings")

    @property
    def clusterrolebindings(self) -> ResourceClient:
        return self.resource("clusterrolebindings")

    @property
    def podmetrics(self) -> ResourceClient:
        return self.resource("podmetrics")

    @property
    def nodemetrics(self) -> ResourceClient:
        return self.resource("nodemetrics")

    @property
    def podcustommetrics(self) -> ResourceClient:
        return self.resource("podcustommetrics")

    def bind(self, namespace: str, pod_name: str, binding: t.Binding):
        """POST the binding subresource.  Returns the server's Status dict
        (upstream's BindingREST returns a Status, not the pod — re-GET the
        pod if the updated object is needed)."""
        return self.api.request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{pod_name}/binding",
            body=self.scheme.encode(binding),
        )

    def bind_batch(self, namespace: str, bindings):
        """POST N bindings as ONE bulk request (pods/bindings:batch): the
        apiserver commits them through one store group commit — the
        scheduler's gang-bind / drained-bind-queue fast path.  Returns one
        outcome per binding, same order: None on success or the ApiError
        that sank that member (members fail independently).

        The request body is PRE-ENCODED: per-item serialized bytes are
        spliced into a literal envelope (one serializer walk per binding,
        over the client's persistent keep-alive connection) instead of
        re-walking the whole envelope dict through json.dumps per
        request; with bind_codec="pybin1" the envelope ships as one
        codec payload (see __init__)."""
        import json as _json

        from ..machinery import ApiError

        path = f"/api/v1/namespaces/{namespace}/pods/bindings:batch"
        items = [self.scheme.encode(b) for b in bindings]
        stream = self._bind_stream
        if stream is not None and not stream.unsupported:
            # zero-copy leg: one length-prefixed frame each way over the
            # persistent per-thread connection.  ANY failure — transport,
            # torn frame, a whole-round server error — takes the HTTP
            # path below for THIS batch (counted loud: a fleet silently
            # off its fast path is an unexplained throughput loss).
            try:
                results = stream.bind_batch(namespace, items)
                return [None if r.get("status") == "Success"
                        else ApiError.from_status(r) for r in results]
            except (ApiError, ConnectionError, OSError) as e:
                from .bindstream import bindstream_fallbacks_total

                bindstream_fallbacks_total.inc()
                # an in-band shed carries the server's backoff hint:
                # honor it BEFORE the HTTP fallback, or every shed round
                # becomes two back-to-back submissions against an
                # apiserver that just said it is overloaded
                retry_after = getattr(e, "retry_after", None)
                if retry_after:
                    import time as _time

                    _time.sleep(min(float(retry_after), 2.0))
        data = None
        if self.bind_codec != "json" and self._bind_codec_ok:
            from ..machinery.codec import get_codec

            payload = get_codec(self.bind_codec).encode(
                {"kind": "BindingList", "apiVersion": "v1", "items": items})
            try:
                data = self.api.request(
                    "POST", path, body=payload,
                    content_type=f"application/x-ktpu-{self.bind_codec}")
            except ApiError as e:
                if getattr(e, "code", 0) not in (400, 415):
                    raise
                # an apiserver that doesn't speak the codec: stay on
                # JSON for the rest of this client's life (re-probing
                # per request would pay a refused round-trip each time)
                self._bind_codec_ok = False
        if data is None:
            body = (b'{"kind":"BindingList","apiVersion":"v1","items":['
                    + b",".join(
                        _json.dumps(d, separators=(",", ":")).encode()
                        for d in items)
                    + b"]}")
            data = self.api.request("POST", path, body=body)
        out = []
        for r in data.get("results", []):
            out.append(None if r.get("status") == "Success"
                       else ApiError.from_status(r))
        return out

    def delete_batch(self, namespace: str, items,
                     grace_seconds: Optional[int] = None):
        """DELETE N pods as ONE bulk request (pods/delete:batch): the
        apiserver commits the whole set through one store group commit —
        the hot-path for gang teardown, podgc sweeps, replicaset
        scale-down, and eviction storms.  Returns one outcome per item,
        same order: None on success or the ApiError that sank that member
        (members fail independently — amortization, not a transaction).

        `items` mixes plain pod names and dicts ({"name", "namespace"?,
        "gracePeriodSeconds"?, "resourceVersion"?}); `grace_seconds`
        applies to every item that doesn't carry its own."""
        from ..machinery import ApiError

        body_items = []
        for it in items:
            d = {"name": it} if isinstance(it, str) else dict(it)
            if grace_seconds is not None and "gracePeriodSeconds" not in d:
                d["gracePeriodSeconds"] = grace_seconds
            body_items.append(d)
        data = self.api.request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/delete:batch",
            body={"kind": "DeleteBatch", "apiVersion": "v1",
                  "items": body_items})
        return [None if r.get("status") == "Success"
                else ApiError.from_status(r)
                for r in data.get("results", [])]

    def evict(self, namespace: str, pod_name: str,
              grace_seconds: "Optional[int]" = None):
        """Eviction subresource: voluntary, PDB-respecting pod removal.
        Raises TooManyRequests (429) when the disruption budget is spent."""
        ev = t.Eviction(grace_period_seconds=grace_seconds)
        ev.metadata.name = pod_name
        ev.metadata.namespace = namespace
        data = self.api.request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{pod_name}/eviction",
            body=self.scheme.encode(ev),
        )
        return self.scheme.decode(data)

    def close(self):
        if self._bind_stream is not None:
            self._bind_stream.close()
        self.api.close()
