"""Persistent zero-copy bind leg: scheduler→apiserver bulk binds as
length-prefixed frames over one upgraded connection.

The bindings:batch HTTP path already splices per-item serialized bytes
into one envelope, but every round still pays full HTTP request assembly
and header parsing on both sides.  This module is the store-wire analog
for the hottest client leg (the PR 9 leftover): the client upgrades ONE
connection per bind worker thread (`GET /api/v1/bindstream?codec=...`,
``Upgrade: ktpu-bind``) and then each round is a single length-prefixed
frame each way (storage/wire.BinFramer — the exact framing the
store<->apiserver wire speaks)::

    request  = frame({"namespace": ns, "items": [<encoded Binding>...]})
    response = frame({"results": [<Status dict>...]})     # same order
             | frame({"error": <Status dict>})            # whole-round

With the ``json`` codec the request payload is SPLICED from the per-item
bytes the caller already serialized (one dumps per binding, zero
envelope re-walk); other codecs (pybin1) encode the plain-data envelope
through machinery/codec's registry, decoded server-side by the same
restricted unpickler the store wire trusts.

Failure contract (the ``client.bindstream`` faultline site covers dial,
round boundaries, and outbound bytes): ANY stream failure — injected
sever/truncate, server restart, torn response — tears down this
thread's stream and raises ConnectionError; the caller (Clientset.
bind_batch) falls back to the per-request HTTP path for that batch, so
no bind outcome is ever lost to the fast path (re-sent bindings are
idempotent: same pod, same node, same chips).  A server that answers
the upgrade with a real HTTP status (an older apiserver's 404) marks
the stream ``unsupported`` STICKY — probing a server that already said
no would pay a refused round-trip per batch forever.  Transient
failures just back off ``REDIAL_FLOOR_SECONDS`` before the next dial.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List

from ..utils import faultline, locksan
from ..utils.metrics import Counter
from ..utils.streams import UpgradeRefused

SITE = "client.bindstream"
BIND_UPGRADE_PROTO = "ktpu-bind"
# do not redial a just-failed stream for this long: the HTTP fallback is
# always available, and hammering a restarting apiserver's upgrade path
# from every bind worker would slow the recovery it is waiting for
REDIAL_FLOOR_SECONDS = 1.0

# Fleet-visible counters (module-level, the informer-family pattern):
# rendered by the apiserver for in-process components and registered into
# the scheduler process's own /metrics registry.  bytes/frames gives the
# bench its bind-leg bytes-per-request; fallbacks nonzero means the fast
# path is NOT engaging (old apiserver, chaos, restart churn).
bindstream_frames_total = Counter(
    "ktpu_bindstream_frames_total",
    "bulk-bind request frames shipped over the persistent bind stream")
bindstream_bytes_total = Counter(
    "ktpu_bindstream_bytes_total",
    "payload bytes shipped over the persistent bind stream")
bindstream_fallbacks_total = Counter(
    "ktpu_bindstream_fallbacks_total",
    "bind batches that fell back to the per-request HTTP path")


class BindStream:
    """One persistent upgraded connection PER THREAD (bind workers call
    concurrently; rounds on one stream are strictly request→response, so
    sharing a stream would serialize the worker pool the way per-thread
    HTTP connections never did)."""

    def __init__(self, api, codec_id: str = "json"):
        from ..machinery.codec import get_codec

        self.api = api
        self.codec_id = codec_id
        get_codec(codec_id)  # typo'd codec fails at construction
        self._local = threading.local()
        # sockets across ALL threads, so close() can sever readers parked
        # in recv() from whichever thread tears the clientset down
        self._socks_lock = locksan.make_lock("client.BindStream._socks_lock")
        self._socks: List[Any] = []
        self._closed = False
        self.unsupported = False  # sticky: the server said 404/400

    # ------------------------------------------------------------ plumbing

    def _framer(self):
        fr = getattr(self._local, "framer", None)
        if fr is not None:
            return fr
        if self._closed:
            raise ConnectionError("bind stream closed")
        if time.monotonic() < getattr(self._local, "down_until", 0.0):
            raise ConnectionError("bind stream backing off after failure")
        faultline.check(SITE)
        try:
            sock = self.api.upgrade(
                f"/api/v1/bindstream?codec={self.codec_id}",
                BIND_UPGRADE_PROTO)
        except UpgradeRefused as e:
            if e.status:  # a live server's real answer: stop probing
                self.unsupported = True
            self._note_down()
            raise
        except (ConnectionError, OSError):
            self._note_down()
            raise
        from ..storage.wire import BinFramer

        fr = BinFramer(sock.makefile("rwb"), self.codec_id, site=SITE)
        self._local.sock = sock
        self._local.framer = fr
        with self._socks_lock:
            self._socks.append(sock)
        return fr

    def _note_down(self):
        self._local.down_until = time.monotonic() + REDIAL_FLOOR_SECONDS

    def _teardown_local(self):
        sock = getattr(self._local, "sock", None)
        self._local.framer = None
        self._local.sock = None
        self._note_down()
        if sock is not None:
            with self._socks_lock:
                try:
                    self._socks.remove(sock)
                except ValueError:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    # --------------------------------------------------------------- verbs

    def bind_batch(self, namespace: str,
                   items: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """One round: N encoded Bindings out, N Status dicts back (same
        order).  Raises ConnectionError on any stream-level failure (the
        caller's cue to take the HTTP path for this batch) and ApiError
        when the server answered the round with a whole-round error."""
        from ..machinery import ApiError
        from ..machinery.codec import CodecError, get_codec

        faultline.check(SITE)
        fr = self._framer()
        if self.codec_id == "json":
            # splice the caller's per-item bytes into a literal envelope:
            # one dumps per binding (paid by the caller, shared with the
            # HTTP fallback), zero envelope re-walk
            payload = (b'{"namespace":' + json.dumps(namespace).encode()
                       + b',"items":['
                       + b",".join(
                           json.dumps(d, separators=(",", ":")).encode()
                           for d in items)
                       + b"]}")
        else:
            payload = get_codec(self.codec_id).encode(
                {"namespace": namespace, "items": items})
        try:
            fr.send_payloads([payload])
            resp = fr.recv()
        except (ConnectionError, OSError, CodecError) as e:
            self._teardown_local()
            raise ConnectionError(f"bind stream round failed: {e}") from e
        err = resp.get("error")
        if err is not None:
            # a whole-round refusal (authz, shed) on a HEALTHY stream:
            # keep the connection, surface the typed error — with the
            # shed's backoff hint preserved (retryAfterSeconds rides the
            # in-band Status; from_status alone would drop it and the
            # caller's HTTP fallback would re-hit an overloaded server
            # immediately)
            e = ApiError.from_status(err)
            try:
                ra = float(err.get("retryAfterSeconds") or 0)
            except (TypeError, ValueError):
                ra = 0.0
            if ra > 0:
                e.retry_after = ra
            raise e
        results = resp.get("results")
        if not isinstance(results, list) or len(results) != len(items):
            self._teardown_local()
            raise ConnectionError(
                f"malformed bind stream response: "
                f"{len(results) if isinstance(results, list) else 'no'} "
                f"results for {len(items)} bindings")
        bindstream_frames_total.inc()
        bindstream_bytes_total.inc(len(payload))
        return results

    def close(self):
        self._closed = True
        with self._socks_lock:
            socks, self._socks = self._socks, []
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
