"""Service VIP data plane — the kube-proxy equivalent (ref: pkg/proxy/;
this is the userspace mode, pkg/proxy/userspace/proxier.go, which is the
honest portable implementation: iptables/ipvs program kernel NAT tables,
which needs root and a real netfilter — here every service port gets a
real listening socket and connections are spliced to a backend).

Shape mirrors the reference: service/endpoints informers feed change
tracking; a sync loop reconciles the active proxy table; backends are
picked round-robin with optional ClientIP session affinity. ClusterIP
virtual routing is exposed through `resolve()`/`connect()` — the node
cannot own 10.96/16, so in-cluster clients (workload containers get
KTPU_PROXY env from the kubelet) route VIPs through the local table
exactly like netfilter would.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..utils.workqueue import RateLimitingQueue
from ..utils import faultline, locksan


class _PortProxy:
    """One listening socket forwarding to a mutable backend set."""

    def __init__(self, listen_host: str, listen_port: int):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((listen_host, listen_port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self.backends: List[Tuple[str, int]] = []
        self.affinity: Optional[str] = None  # None | "ClientIP"
        self.affinity_ttl = 10800.0
        self._affinity_map: Dict[str, Tuple[Tuple[str, int], float]] = {}
        self._rr = 0
        self._lock = locksan.make_lock("_PortProxy._lock")
        self._closed = False
        self.connections = 0
        self.errors = 0
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def set_backends(self, backends: List[Tuple[str, int]]):
        with self._lock:
            self.backends = list(backends)

    def _pick(self, client_ip: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            if not self.backends:
                return None
            if self.affinity == "ClientIP":
                hit = self._affinity_map.get(client_ip)
                if hit and time.monotonic() - hit[1] < self.affinity_ttl \
                        and hit[0] in self.backends:
                    self._affinity_map[client_ip] = (hit[0], time.monotonic())
                    return hit[0]
            be = self.backends[self._rr % len(self.backends)]
            self._rr += 1
            if self.affinity == "ClientIP":
                self._affinity_map[client_ip] = (be, time.monotonic())
            return be

    def _accept_loop(self):
        while not self._closed:
            try:
                client, addr = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(client, addr[0]), daemon=True
            ).start()

    def _handle(self, client: socket.socket, client_ip: str):
        be = self._pick(client_ip)
        if be is None:
            self.errors += 1
            client.close()
            return
        try:
            # proxy.upstream: seeded chaos severs/delays the proxy->backend
            # leg — the client-facing error path must stay clean
            faultline.check("proxy.upstream")
            upstream = socket.create_connection(be, timeout=10)
        except OSError:
            self.errors += 1
            client.close()
            return
        self.connections += 1
        for a, b in ((client, upstream), (upstream, client)):
            threading.Thread(target=self._splice, args=(a, b), daemon=True).start()

    @staticmethod
    def _splice(src: socket.socket, dst: socket.socket):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    def close(self):
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class Proxier:
    """Per-node service proxy: one _PortProxy per (service, port)."""

    def __init__(
        self,
        clientset: Clientset,
        factory: Optional[InformerFactory] = None,
        listen_host: str = "127.0.0.1",
    ):
        self.cs = clientset
        self.factory = factory or InformerFactory(clientset)
        self.listen_host = listen_host
        self.queue = RateLimitingQueue()
        # (ns, svc_name, port_name) -> _PortProxy
        self._proxies: Dict[Tuple[str, str, str], _PortProxy] = {}
        # (cluster_ip, service_port) -> local (host, port); the VIP table
        self._vips: Dict[Tuple[str, int], Tuple[str, int]] = {}
        # (ns, svc_name) -> vip keys owned by that service, for pruning
        self._svc_vips: Dict[Tuple[str, str], set] = {}
        self._lock = locksan.make_lock("Proxier._lock")
        self._stop = threading.Event()
        self._own_factory = factory is None

    def start(self):
        self.services = self.factory.informer("services")
        self.endpoints = self.factory.informer("endpoints")
        self.services.add_handler(
            on_add=self._enqueue, on_update=lambda _o, n: self._enqueue(n),
            on_delete=self._enqueue,
        )
        self.endpoints.add_handler(
            on_add=self._enqueue, on_update=lambda _o, n: self._enqueue(n),
            on_delete=self._enqueue,
        )
        if self._own_factory:
            self.factory.start_all()
            self.factory.wait_for_sync()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        return self

    def _enqueue(self, obj):
        self.queue.add(f"{obj.metadata.namespace}/{obj.metadata.name}")

    def _worker(self):
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                self._sync(key)
                self.queue.forget(key)
            except Exception:  # noqa: BLE001
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    def _sync(self, key: str):
        svc = self.services.get(key)
        ns, name = key.split("/", 1)
        if svc is None or svc.spec.cluster_ip == "None":
            self._remove_service(ns, name)
            return
        eps = self.endpoints.get(key)
        bind_error: Optional[OSError] = None
        new_vips = set()
        for sp in svc.spec.ports:
            pkey = (ns, name, sp.name)
            backends = self._backends_for(eps, sp)
            with self._lock:
                proxy = self._proxies.get(pkey)
                want_port = sp.node_port if svc.spec.type == "NodePort" else 0
                if proxy is not None and want_port and proxy.port != want_port:
                    self._proxies.pop(pkey).close()  # nodePort changed: rebind
                    proxy = None
                if proxy is None:
                    try:
                        proxy = _PortProxy(self.listen_host, want_port)
                    except OSError as e:
                        bind_error = e  # raise after the loop -> rate-limited retry
                        continue
                    self._proxies[pkey] = proxy
                proxy.affinity = svc.spec.session_affinity or None
                proxy.set_backends(backends)
                if svc.spec.cluster_ip:
                    vkey = (svc.spec.cluster_ip, sp.port)
                    self._vips[vkey] = (self.listen_host, proxy.port)
                    new_vips.add(vkey)
        with self._lock:
            # drop ports removed from the spec + VIP entries no longer valid
            live = {(ns, name, sp.name) for sp in svc.spec.ports}
            for pkey in [
                k for k in self._proxies if k[:2] == (ns, name) and k not in live
            ]:
                self._proxies.pop(pkey).close()
            for vkey in self._svc_vips.get((ns, name), set()) - new_vips:
                self._vips.pop(vkey, None)
            self._svc_vips[(ns, name)] = new_vips
        if bind_error is not None:
            raise bind_error

    def _backends_for(self, eps: Optional[t.Endpoints], sp) -> List[Tuple[str, int]]:
        if eps is None:
            return []
        out = []
        for subset in eps.subsets:
            port = None
            for ep in subset.ports:
                if ep.name == sp.name or (not ep.name and not sp.name):
                    port = ep.port
                    break
            if port is None and len(subset.ports) == 1:
                port = subset.ports[0].port
            if port is None:
                continue
            for addr in subset.addresses:
                out.append((addr.ip, port))
        return out

    def _remove_service(self, ns: str, name: str):
        with self._lock:
            for pkey in [k for k in self._proxies if k[:2] == (ns, name)]:
                self._proxies.pop(pkey).close()
            for vkey in self._svc_vips.pop((ns, name), set()):
                self._vips.pop(vkey, None)

    # ------------------------------------------------------------ client API

    def resolve(self, cluster_ip: str, port: int) -> Optional[Tuple[str, int]]:
        """VIP -> actual (host, port), as netfilter DNAT would."""
        with self._lock:
            return self._vips.get((cluster_ip, port))

    def connect(self, cluster_ip: str, port: int, timeout: float = 10) -> socket.socket:
        target = self.resolve(cluster_ip, port)
        if target is None:
            raise ConnectionRefusedError(f"no proxy for {cluster_ip}:{port}")
        faultline.check("proxy.upstream")
        return socket.create_connection(target, timeout=timeout)

    def node_port_for(self, ns: str, name: str, port_name: str = "") -> Optional[int]:
        with self._lock:
            p = self._proxies.get((ns, name, port_name))
            return p.port if p else None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "services": len({k[:2] for k in self._proxies}),
                "ports": len(self._proxies),
                "connections": sum(p.connections for p in self._proxies.values()),
                "errors": sum(p.errors for p in self._proxies.values()),
            }

    def stop(self):
        self._stop.set()
        if self._own_factory:
            self.factory.stop_all()
        with self._lock:
            for p in self._proxies.values():
                p.close()
            self._proxies.clear()
            self._vips.clear()
            self._svc_vips.clear()
