"""Rule-table proxier — the iptables-mode analog.

Ref: pkg/proxy/iptables/proxier.go (1756 LoC) — there, services + endpoints
compile into kernel NAT chains (KUBE-SERVICES → KUBE-SVC-* → KUBE-SEP-*)
with probability-weighted DNAT, so the steady-state data path costs zero
userspace hops. Portably, the same architecture is: watch events mark the
table dirty, a sync pass *compiles* the full rule table atomically (the
iptables-restore batch), and resolution is a pure O(1) lookup with weighted
backend choice — no per-service sockets (contrast: proxier.py, the
userspace mode). `dump()` renders the compiled table in iptables-save
syntax for operator inspection (`ktpu proxy-rules`).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..utils import locksan


class _ServiceRules:
    __slots__ = ("namespace", "name", "port_name", "protocol", "cluster_ip",
                 "port", "node_port", "affinity", "backends")

    def __init__(self, namespace, name, port_name, protocol, cluster_ip, port,
                 node_port, affinity, backends):
        self.namespace = namespace
        self.name = name
        self.port_name = port_name
        self.protocol = protocol
        self.cluster_ip = cluster_ip
        self.port = port
        self.node_port = node_port
        self.affinity = affinity
        self.backends = backends  # [(ip, port)]


class RuleTableProxier:
    """Compiles the service/endpoint state into an immutable lookup table,
    swapped atomically on every sync (the iptables-restore model)."""

    def __init__(self, clientset: Clientset, factory: Optional[InformerFactory] = None,
                 min_sync_period: float = 0.05):
        self.cs = clientset
        self.factory = factory or InformerFactory(clientset)
        self._own_factory = factory is None
        self.min_sync_period = min_sync_period
        self._dirty = threading.Event()
        self._stop = threading.Event()
        # immutable compiled tables, swapped as a unit
        self._by_vip: Dict[Tuple[str, int], _ServiceRules] = {}
        self._by_nodeport: Dict[int, _ServiceRules] = {}
        self._affinity: Dict[Tuple[str, str], Tuple[Tuple[str, int], float]] = {}
        self._affinity_lock = locksan.make_lock("RuleTableProxier._affinity_lock")  # written by resolve AND sync
        self._affinity_ttl = 10800.0
        self.sync_count = 0

    # --------------------------------------------------------------- control

    def start(self):
        self.services = self.factory.informer("services")
        self.endpoints = self.factory.informer("endpoints")
        mark = lambda *_a, **_k: self._dirty.set()  # noqa: E731
        for inf in (self.services, self.endpoints):
            inf.add_handler(on_add=mark, on_update=lambda _o, _n: self._dirty.set(),
                            on_delete=mark)
        if self._own_factory:
            self.factory.start_all()
            self.factory.wait_for_sync()
        self._dirty.set()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._dirty.set()
        if self._own_factory:
            self.factory.stop_all()

    def _loop(self):
        while not self._stop.is_set():
            self._dirty.wait(1.0)
            if self._stop.is_set():
                return
            if not self._dirty.is_set():
                continue
            self._dirty.clear()
            time.sleep(self.min_sync_period)  # coalesce bursts
            self.sync_all()

    # --------------------------------------------------------------- compile

    def sync_all(self):
        """Recompile the whole table (iptables-restore semantics: one atomic
        swap, partial state never visible)."""
        by_vip: Dict[Tuple[str, int], _ServiceRules] = {}
        by_nodeport: Dict[int, _ServiceRules] = {}
        for svc in self.services.list():
            if svc.spec.cluster_ip in ("", "None"):
                continue
            eps = self.endpoints.get(svc.key())
            for sp in svc.spec.ports:
                backends = self._backends_for(eps, sp)
                rules = _ServiceRules(
                    namespace=svc.metadata.namespace, name=svc.metadata.name,
                    port_name=sp.name, protocol=sp.protocol or "TCP",
                    cluster_ip=svc.spec.cluster_ip, port=sp.port,
                    node_port=sp.node_port or 0,
                    affinity=svc.spec.session_affinity or "",
                    backends=backends,
                )
                by_vip[(svc.spec.cluster_ip, sp.port)] = rules
                if rules.node_port:
                    by_nodeport[rules.node_port] = rules
        self._by_vip = by_vip  # atomic reference swap
        self._by_nodeport = by_nodeport
        # prune affinity state: expired entries and deleted services — the
        # map otherwise grows one entry per distinct client IP forever
        live = {f"{r.namespace}/{r.name}:{r.port_name}" for r in by_vip.values()}
        now = time.monotonic()
        with self._affinity_lock:
            for k in [
                k for k, v in self._affinity.items()
                if k[0] not in live or now - v[1] >= self._affinity_ttl
            ]:
                del self._affinity[k]  # prune in place: concurrent resolve()
                # writes between snapshot and swap must not be lost
        self.sync_count += 1

    @staticmethod
    def _backends_for(eps: Optional[t.Endpoints], sp) -> List[Tuple[str, int]]:
        if eps is None:
            return []
        out = []
        for subset in eps.subsets:
            port = None
            for ep in subset.ports:
                if not sp.name or ep.name == sp.name:
                    port = ep.port
                    break
            if port is None and subset.ports:
                port = subset.ports[0].port
            if port is None:
                continue
            for addr in subset.addresses:
                out.append((addr.ip, port))
        return sorted(out)

    # --------------------------------------------------------------- resolve

    def resolve(self, cluster_ip: str, port: int,
                client_ip: str = "") -> Optional[Tuple[str, int]]:
        """DNAT decision: weighted-random backend (the iptables statistic
        module), with ClientIP affinity when the service asks for it."""
        rules = self._by_vip.get((cluster_ip, port))
        return self._pick(rules, client_ip)

    def resolve_node_port(self, node_port: int,
                          client_ip: str = "") -> Optional[Tuple[str, int]]:
        return self._pick(self._by_nodeport.get(node_port), client_ip)

    def _pick(self, rules: Optional[_ServiceRules],
              client_ip: str) -> Optional[Tuple[str, int]]:
        if rules is None or not rules.backends:
            return None
        if rules.affinity == "ClientIP" and client_ip:
            akey = (f"{rules.namespace}/{rules.name}:{rules.port_name}", client_ip)
            now = time.monotonic()
            with self._affinity_lock:
                hit = self._affinity.get(akey)
                if hit and now - hit[1] < self._affinity_ttl and hit[0] in rules.backends:
                    self._affinity[akey] = (hit[0], now)
                    return hit[0]
                chosen = random.choice(rules.backends)
                self._affinity[akey] = (chosen, now)
            return chosen
        return random.choice(rules.backends)

    # ------------------------------------------------------------------ dump

    @staticmethod
    def _chain(prefix: str, *parts: str) -> str:
        h = hashlib.sha256("/".join(parts).encode()).hexdigest()[:16].upper()
        return f"{prefix}-{h}"

    def dump(self) -> str:
        """Render the compiled table in iptables-save syntax (KTPU-SERVICES /
        KTPU-SVC-* / KTPU-SEP-* mirror the reference's KUBE-* chains)."""
        lines = ["*nat", ":KTPU-SERVICES - [0:0]", ":KTPU-NODEPORTS - [0:0]"]
        svc_lines, sep_lines = [], []
        for (vip, port), rules in sorted(self._by_vip.items()):
            svc_chain = self._chain("KTPU-SVC", rules.namespace, rules.name,
                                    rules.port_name)
            lines.append(f":{svc_chain} - [0:0]")
            svc_lines.append(
                f"-A KTPU-SERVICES -d {vip}/32 -p {rules.protocol.lower()} "
                f"--dport {port} -m comment --comment "
                f'"{rules.namespace}/{rules.name}:{rules.port_name}" -j {svc_chain}'
            )
            if rules.node_port:
                svc_lines.append(
                    f"-A KTPU-NODEPORTS -p {rules.protocol.lower()} "
                    f"--dport {rules.node_port} -j {svc_chain}"
                )
            n = len(rules.backends)
            for i, (bip, bport) in enumerate(rules.backends):
                sep_chain = self._chain("KTPU-SEP", rules.namespace, rules.name,
                                        rules.port_name, f"{bip}:{bport}")
                lines.append(f":{sep_chain} - [0:0]")
                prob = ""
                if i < n - 1:
                    prob = (f" -m statistic --mode random "
                            f"--probability {1.0 / (n - i):.5f}")
                sep_lines.append(f"-A {svc_chain}{prob} -j {sep_chain}")
                sep_lines.append(
                    f"-A {sep_chain} -p {rules.protocol.lower()} "
                    f"-j DNAT --to-destination {bip}:{bport}"
                )
        lines.extend(svc_lines)
        lines.extend(sep_lines)
        lines.append("COMMIT")
        return "\n".join(lines) + "\n"
