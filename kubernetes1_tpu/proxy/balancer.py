"""Request-level (L7) Service load balancing — the least-inflight
balancer behind the serving data plane.

The userspace proxier (proxier.py) splices CONNECTIONS: a backend is
picked once per TCP connect, round-robin, and a keep-alive client pins
every request it will ever send to that one pick.  For inference
serving that is the wrong unit — one slow replica (a deep batch queue,
a compiling shape) should shed load per REQUEST, not capture a client
for the life of its socket.  This module is the Service leg's L7 mode:

- every HTTP request is routed independently: the backend with the
  fewest in-flight requests wins; ties break by power-of-two-choices
  (two seeded samples from the tied set, fewest-total-requests wins) so
  tied backends spread without a global counter;
- endpoints updates SWAP the backend set without dropping in-flight
  requests: a removed backend stops being picked and drains — its
  in-flight responses finish on the open sockets (the zero-downtime
  rollout contract: terminating pods leave Endpoints first, the drain
  falls out of the swap semantics);
- a request that fails BEFORE any response byte reaches the client
  (dial refused, injected drop, backend reset) retries on a surviving
  backend (bounded attempts, `client/retry.note_retry` bookkeeping) —
  an acked request is one whose response was delivered, and those are
  never lost;
- everything rides `utils/eventloop.shared_loop()` per the KTPU015/016
  invariants: non-blocking sockets, state machines on the dispatcher,
  no per-connection threads, faultline via `check_deferred` (a delay
  fault re-arms with `call_later`, it never sleeps the loop).

Faultline sites: ``proxy.upstream`` (the dial), ``proxy.upstream_send``
(the request-forward leg) — scripts/chaos.py --schedule serve puts both
under seeded fire.
"""

from __future__ import annotations

import random
import socket
from typing import Callable, Dict, List, Optional, Tuple

from ..client import retry as _retry
from ..utils import eventloop, faultline

Addr = Tuple[str, int]

_MAX_HEADER = 65536
_MAX_BODY = 4 * 1024 * 1024
_CONNECT_TIMEOUT = 5.0
_REQUEST_TIMEOUT = 60.0


class _Backend:
    """Per-backend routing state.  Mutated on the loop thread only."""

    __slots__ = ("addr", "inflight", "requests", "errors", "draining")

    def __init__(self, addr: Addr):
        self.addr = addr
        self.inflight = 0
        self.requests = 0
        self.errors = 0
        self.draining = False


def _parse_headers(raw: bytes):
    """(request|status) line, header dict (lower-cased keys), raw header
    block length.  Returns None while incomplete."""
    end = raw.find(b"\r\n\r\n")
    if end < 0:
        return None
    lines = raw[:end].split(b"\r\n")
    first = lines[0].decode("latin-1")
    headers: Dict[str, str] = {}
    for ln in lines[1:]:
        k, _, v = ln.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    return first, headers, end + 4


class _Upstream:
    """One in-flight request's backend leg: non-blocking connect, send
    the buffered request, relay the response to the client as bytes
    arrive, detect response completion from the framing."""

    def __init__(self, bal: "LeastInflightBalancer", client: "_Client",
                 backend: _Backend, request: bytes):
        self.bal = bal
        self.client = client
        self.backend = backend
        self.request = request
        self.sock: Optional[socket.socket] = None
        self.buf = bytearray()          # response bytes before headers parse
        self.headers_done = False
        self.remaining = -1             # body bytes left (-1: until close)
        self.chunk_state = None         # chunked framing scanner state
        self.closed = False
        self._timer = None

    # ------------------------------------------------------------- dial

    def start(self):
        loop = self.bal._loop
        try:
            delay = faultline.check_deferred("proxy.upstream")
        except faultline.FaultInjected:
            self.backend.errors += 1
            self.fail("upstream dial fault")
            return
        if delay:
            self._timer = loop.call_later(delay, self._dial)
            return
        self._dial()

    def _dial(self):
        import errno

        loop = self.bal._loop
        try:
            self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self.sock.setblocking(False)
            # non-blocking connect: 0 or EINPROGRESS here, completion
            # lands as writability
            rc = self.sock.connect_ex(self.backend.addr)
            if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
                raise OSError(rc, "connect failed")
        except OSError:
            self.fail("upstream connect error")
            return
        loop.register(self.sock, eventloop.selectors.EVENT_WRITE,
                      self._on_connected)
        loop.add_connection()
        self._timer = loop.call_later(_CONNECT_TIMEOUT, self._on_timeout)

    def _on_timeout(self):
        if not self.closed and not self.headers_done:
            self.fail("upstream connect timeout")

    def _on_connected(self, mask: int):
        if self.closed:
            return
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err:
            self.fail("upstream connect refused")
            return
        # park the fd during the fault gate: a writable socket would
        # otherwise spin the dispatcher through any delay window
        self.bal._loop.unregister(self.sock)
        try:
            delay = faultline.check_deferred("proxy.upstream_send")
        except faultline.FaultInjected:
            self.backend.errors += 1
            self.fail("upstream send fault")
            return
        if delay:
            self._timer = self.bal._loop.call_later(delay, self._begin_send)
            return
        self._begin_send()

    def _begin_send(self):
        if self.closed:
            return
        self._out = memoryview(self.request)
        self.bal._loop.register(self.sock, eventloop.selectors.EVENT_WRITE,
                                self._on_writable)

    def _on_writable(self, mask: int):
        if self.closed:
            return
        while len(self._out):
            try:
                n = self.sock.send(self._out)  # ktpulint: ignore[KTPU016] socket is setblocking(False); a full kernel buffer raises BlockingIOError and we re-arm on writability
            except (BlockingIOError, InterruptedError):
                return  # still registered for EVENT_WRITE: resume there
            except OSError:
                self.fail("upstream send error")
                return
            self._out = self._out[n:]
        self.bal._loop.modify(self.sock, eventloop.selectors.EVENT_READ,
                              self._on_readable)

    # ---------------------------------------------------------- response

    def _on_readable(self, mask: int):
        if self.closed:
            return
        try:
            data = self.sock.recv(65536)  # ktpulint: ignore[KTPU016] socket is setblocking(False); recv returns or raises BlockingIOError, never stalls the loop
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            if self.headers_done and self.remaining <= 0 \
                    and self.chunk_state is None:
                self.finish()
            else:
                self.fail("upstream closed early")
            return
        if not self.headers_done:
            self.buf += data
            if len(self.buf) > _MAX_HEADER:
                self.fail("upstream header overflow")
                return
            parsed = _parse_headers(bytes(self.buf))
            if parsed is None:
                return
            status, headers, hlen = parsed
            body = bytes(self.buf[hlen:])
            self.headers_done = True
            te = headers.get("transfer-encoding", "")
            if "chunked" in te:
                self.chunk_state = {"remaining": 0, "buf": b"", "done": False}
                self.remaining = 0
            elif "content-length" in headers:
                try:
                    self.remaining = int(headers["content-length"])
                except ValueError:
                    self.fail("bad content-length")
                    return
            else:
                self.remaining = -1  # until-close framing
            # rewrite Connection for the client leg: until-close framing
            # forces close; otherwise honor what the client asked for
            conn = ("close" if self.remaining == -1 or self.client.want_close
                    else "keep-alive")
            self.client.response_close = (conn == "close")
            out = [status.encode("latin-1")]
            for k, v in headers.items():
                if k == "connection":
                    continue
                out.append(f"{k}: {v}".encode("latin-1"))
            out.append(b"connection: " + conn.encode())
            self.client.send(b"\r\n".join(out) + b"\r\n\r\n")
            self.client.acked = True
            if body:
                self._relay_body(body)
        else:
            self._relay_body(data)

    def _relay_body(self, data: bytes):
        self.client.send(data)
        if self.chunk_state is not None:
            self._scan_chunks(data)
            if self.chunk_state["done"]:
                self.finish()
        elif self.remaining >= 0:
            self.remaining -= len(data)
            if self.remaining <= 0:
                self.finish()

    def _scan_chunks(self, data: bytes):
        """Minimal chunked-framing scanner: finds the terminal 0-size
        chunk so response completion is detected without re-framing."""
        st = self.chunk_state
        st["buf"] += data
        while True:
            if st["remaining"] > 0:
                take = min(st["remaining"], len(st["buf"]))
                st["buf"] = st["buf"][take:]
                st["remaining"] -= take
                if st["remaining"] > 0:
                    return
            nl = st["buf"].find(b"\r\n")
            if nl < 0:
                return
            line = st["buf"][:nl].strip()
            st["buf"] = st["buf"][nl + 2:]
            if not line:
                continue  # chunk-data trailing CRLF
            try:
                size = int(line.split(b";")[0], 16)
            except ValueError:
                continue
            if size == 0:
                st["done"] = True
                return
            st["remaining"] = size + 2  # chunk data + its CRLF

    # ---------------------------------------------------------- teardown

    def _close_sock(self):
        if self._timer is not None:
            self._timer.cancel()
        if self.sock is not None:
            self.bal._loop.unregister(self.sock)
            self.bal._loop.remove_connection()
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def finish(self):
        if self.closed:
            return
        self.closed = True
        self._close_sock()
        self.backend.requests += 1
        self.bal._release(self.backend)
        self.client.request_done()

    def fail(self, why: str):
        if self.closed:
            return
        self.closed = True
        self._close_sock()
        self.backend.errors += 1
        self.bal._release(self.backend)
        self.client.upstream_failed(why)


class _Client:
    """One accepted client connection: parse HTTP/1.1 requests off a
    non-blocking socket, dispatch each through the balancer's pick, and
    write the relayed response (write-ready-driven outbuf)."""

    def __init__(self, bal: "LeastInflightBalancer", sock: socket.socket):
        self.bal = bal
        self.sock = sock
        self.buf = bytearray()
        self.outbuf = bytearray()
        self.closed = False
        self.busy = False            # one request in flight per connection
        self.close_after_flush = False
        self.want_close = False      # client asked Connection: close
        self.response_close = False  # response leg decided to close
        self.acked = False           # response bytes reached the client
        self.attempts = 0
        self.tried: set = set()
        self.request: bytes = b""
        self._timer = None
        sock.setblocking(False)
        bal._loop.register(sock, eventloop.selectors.EVENT_READ,
                           self._on_readable)
        bal._loop.add_connection()

    # ------------------------------------------------------------- parse

    def _on_readable(self, mask: int):
        if self.closed:
            return
        try:
            data = self.sock.recv(65536)  # ktpulint: ignore[KTPU016] socket is setblocking(False); recv returns or raises BlockingIOError, never stalls the loop
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            self.close()
            return
        self.buf += data
        if not self.busy:
            self._try_dispatch()

    def _try_dispatch(self):
        parsed = _parse_headers(bytes(self.buf))
        if parsed is None:
            if len(self.buf) > _MAX_HEADER:
                self._respond_error(431, "headers too large")
            return
        first, headers, hlen = parsed
        try:
            clen = int(headers.get("content-length") or 0)
        except ValueError:
            self._respond_error(400, "bad content-length")
            return
        if clen > _MAX_BODY:
            self._respond_error(413, "body too large")
            return
        if len(self.buf) < hlen + clen:
            return  # body still arriving
        body = bytes(self.buf[hlen:hlen + clen])
        del self.buf[:hlen + clen]
        self.want_close = (headers.get("connection", "").lower() == "close")
        # rebuild the upstream request: per-request routing means the
        # backend must not hold the connection open on its side
        out = [first.encode("latin-1")]
        for k, v in headers.items():
            if k in ("connection", "proxy-connection"):
                continue
            out.append(f"{k}: {v}".encode("latin-1"))
        out.append(b"connection: close")
        self.request = b"\r\n".join(out) + b"\r\n\r\n" + body
        self.busy = True
        self.acked = False
        self.attempts = 0
        self.tried = set()
        self.bal.requests_total += 1
        self._timer = self.bal._loop.call_later(_REQUEST_TIMEOUT,
                                                self._on_request_timeout)
        self._dispatch()

    def _on_request_timeout(self):
        if self.busy and not self.closed:
            self.close()

    # ---------------------------------------------------------- dispatch

    def _dispatch(self):
        backend = self.bal._pick(exclude=self.tried)
        if backend is None and self.tried:
            backend = self.bal._pick()  # all tried: allow re-pick
        if backend is None:
            self._respond_error(503, "no backends")
            return
        self.tried.add(backend.addr)
        backend.inflight += 1
        self.bal.picks[backend.addr] = self.bal.picks.get(backend.addr, 0) + 1
        _Upstream(self.bal, self, backend, self.request).start()

    def upstream_failed(self, why: str):
        if self.closed:
            return
        if self.acked:
            # response bytes already reached the client: the truncation
            # is visible there — never splice a second backend's bytes
            # onto a half-delivered response
            self.bal.errors_total += 1
            self.close()
            return
        self.attempts += 1
        if self.attempts <= self.bal.max_retries:
            _retry.note_retry("proxy.upstream")
            self.bal.retries_total += 1
            self._dispatch()
            return
        self._respond_error(502, why)

    def request_done(self):
        if self.closed:
            return
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.busy = False
        if self.response_close or self.want_close:
            self.close_after_flush = True
            self._flush()
            return
        if self.buf:
            self._try_dispatch()  # pipelined next request already buffered

    # ------------------------------------------------------------- write

    def send(self, data: bytes):
        if self.closed:
            return
        self.outbuf += data
        self._flush()

    def _flush(self):
        if self.closed:
            return
        if self.outbuf:
            try:
                n = self.sock.send(bytes(self.outbuf))  # ktpulint: ignore[KTPU016] socket is setblocking(False); a full kernel buffer raises BlockingIOError and we re-arm on writability
                del self.outbuf[:n]
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                self.close()
                return
        if self.outbuf:
            self.bal._loop.modify(
                self.sock,
                eventloop.selectors.EVENT_READ
                | eventloop.selectors.EVENT_WRITE,
                self._on_event)
        else:
            if self.close_after_flush:
                self.close()
                return
            self.bal._loop.modify(self.sock, eventloop.selectors.EVENT_READ,
                                  self._on_readable)

    def _on_event(self, mask: int):
        if mask & eventloop.selectors.EVENT_WRITE:
            self._flush()
        if not self.closed and mask & eventloop.selectors.EVENT_READ:
            self._on_readable(mask)

    def _respond_error(self, code: int, why: str):
        self.bal.errors_total += 1
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.busy = False
        body = ('{"error":"%s"}' % why).encode()
        self.send(b"HTTP/1.1 %d x\r\ncontent-type: application/json\r\n"
                  b"content-length: %d\r\nconnection: close\r\n\r\n%s"
                  % (code, len(body), body))
        self.close_after_flush = True
        self._flush()

    def close(self):
        if self.closed:
            return
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
        self.bal._loop.unregister(self.sock)
        self.bal._loop.remove_connection()
        try:
            self.sock.close()
        except OSError:
            pass
        self.bal._clients.discard(self)


class LeastInflightBalancer:
    """See module docstring.  Policies (``policy=``):

    - ``least_inflight`` (default): fewest in-flight wins, ties by
      power-of-two-choices over the tied set;
    - ``round_robin`` / ``random``: the A/B baselines the bench's
      skewed-backend comparison runs against.

    ``set_backends`` is thread-safe (hops to the loop); draining
    backends keep serving their in-flight requests and leave the table
    when the last one finishes."""

    def __init__(self, listen_host: str = "127.0.0.1", port: int = 0,
                 seed: int = 0, policy: str = "least_inflight",
                 max_retries: int = 2):
        if policy not in ("least_inflight", "round_robin", "random"):
            raise ValueError(f"unknown policy {policy!r}")
        self.policy = policy
        self.max_retries = max_retries
        self._rng = random.Random(seed)
        self._loop = eventloop.shared_loop()
        self._table: Dict[Addr, _Backend] = {}  # loop-thread only
        self._clients: set = set()
        self._rr = 0
        self.requests_total = 0
        self.retries_total = 0
        self.errors_total = 0
        self.picks: Dict[Addr, int] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((listen_host, port))
        self._sock.listen(128)
        self._sock.setblocking(False)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._loop.call_soon(lambda: self._loop.register(
            self._sock, eventloop.selectors.EVENT_READ, self._on_accept))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ---------------------------------------------------------- backends

    def set_backends(self, addrs: List[Addr]):
        """Swap the pick set (thread-safe).  Removed backends drain:
        in-flight requests finish, new requests never land on them."""
        addrs = list(addrs)
        self._loop.call_soon(lambda: self._set_backends_on_loop(addrs))

    def _set_backends_on_loop(self, addrs: List[Addr]):
        want = set(addrs)
        for addr in want:
            b = self._table.get(addr)
            if b is None:
                self._table[addr] = _Backend(addr)
            else:
                b.draining = False
        for addr, b in list(self._table.items()):
            if addr not in want:
                if b.inflight > 0:
                    b.draining = True
                else:
                    del self._table[addr]

    def _release(self, backend: _Backend):
        backend.inflight -= 1
        if backend.draining and backend.inflight <= 0:
            # `del`, not `.pop`: the interprocedural KTPU016 pass
            # resolves attribute calls by NAME and would chase every
            # `pop` in the tree (e.g. SchedulingQueue.pop, which blocks)
            if backend.addr in self._table:
                del self._table[backend.addr]

    def _pick(self, exclude: Optional[set] = None) -> Optional[_Backend]:
        avail = [b for b in self._table.values() if not b.draining
                 and not (exclude and b.addr in exclude)]
        if not avail:
            return None
        if self.policy == "round_robin":
            avail.sort(key=lambda b: b.addr)
            b = avail[self._rr % len(avail)]
            self._rr += 1
            return b
        if self.policy == "random":
            return self._rng.choice(avail)
        low = min(b.inflight for b in avail)
        tied = [b for b in avail if b.inflight == low]
        if len(tied) == 1:
            return tied[0]
        # power-of-two-choices over the tie: two seeded samples, fewest
        # cumulative attempts wins — spreads without a global counter
        # (errors count as attempts, else a dead backend's empty ledger
        # would win every tie and each request would pay a retry)
        a, c = self._rng.sample(tied, 2)
        return a if a.requests + a.errors <= c.requests + c.errors else c

    # ------------------------------------------------------------ accept

    def _on_accept(self, mask: int):
        for _ in range(64):
            try:
                sock, _addr = self._sock.accept()  # ktpulint: ignore[KTPU016] listen socket is setblocking(False); accept returns or raises BlockingIOError, never stalls the loop
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            self._clients.add(_Client(self, sock))

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Counters + per-backend table (snapshot; reader-friendly)."""
        table = {
            f"{a[0]}:{a[1]}": {"inflight": b.inflight,
                               "requests": b.requests,
                               "errors": b.errors,
                               "draining": b.draining}
            for a, b in list(self._table.items())
        }
        return {
            "policy": self.policy,
            "requests": self.requests_total,
            "retries": self.retries_total,
            "errors": self.errors_total,
            "backends": table,
            "picks": {f"{a[0]}:{a[1]}": n
                      for a, n in list(self.picks.items())},
        }

    def stop(self):
        if self._closed:
            return
        self._closed = True

        def _teardown():
            self._loop.unregister(self._sock)
            try:
                self._sock.close()
            except OSError:
                pass
            for c in list(self._clients):
                c.close()

        self._loop.call_soon(_teardown)


class EndpointsBalancerSync:
    """Feeds a balancer from a Service's Endpoints object: informer
    updates swap the backend set; only ready ``addresses`` are picked —
    ``notReadyAddresses`` (draining pods) fall out of the set, which IS
    the drain signal.  Pod IPs are synthetic in an in-process cluster,
    so the RESOLVER maps an address's pod identity (targetRef, ip as
    fallback) + port to the real (host, port) a backend listens on
    (workloads/servefleet keeps that registry); a real deployment's
    resolver is the identity function on (ip, port)."""

    def __init__(self, balancer: LeastInflightBalancer, factory,
                 namespace: str, service: str,
                 resolver: Optional[Callable[[str, int],
                                             Optional[Addr]]] = None):
        self.balancer = balancer
        self.namespace = namespace
        self.service = service
        self.resolver = resolver or (lambda ip, port: (ip, port))
        self._informer = factory.informer("endpoints")
        self._informer.add_handler(
            on_add=self._on_change,
            on_update=lambda _o, n: self._on_change(n),
            on_delete=lambda o: self._on_change(None, deleted=o),
        )

    def _key(self, obj) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def _on_change(self, obj, deleted=None):
        target = f"{self.namespace}/{self.service}"
        if deleted is not None and self._key(deleted) == target:
            self.balancer.set_backends([])
            return
        if obj is None or self._key(obj) != target:
            return
        addrs: List[Addr] = []
        for subset in obj.subsets:
            port = subset.ports[0].port if subset.ports else 0
            for a in subset.addresses:
                resolved = self.resolver(a.target_ref or a.ip, port)
                if resolved is not None:
                    addrs.append(resolved)
        self.balancer.set_backends(addrs)
