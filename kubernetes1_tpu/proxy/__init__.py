from .proxier import Proxier

__all__ = ["Proxier"]
