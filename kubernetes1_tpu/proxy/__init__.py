from .balancer import EndpointsBalancerSync, LeastInflightBalancer
from .proxier import Proxier
from .rules import RuleTableProxier

__all__ = ["EndpointsBalancerSync", "LeastInflightBalancer", "Proxier",
           "RuleTableProxier"]
