from .proxier import Proxier
from .rules import RuleTableProxier

__all__ = ["Proxier", "RuleTableProxier"]
