"""IPVS-mode service proxier analog.

Ref: pkg/proxy/ipvs/proxier.go (1850 LoC).  What distinguishes IPVS mode
from the iptables/rule-table mode is not the watch plumbing (shared shape)
but the data path semantics, reproduced here:

- virtual servers with REAL per-backend state (weights, active/inactive
  connection counts) instead of stateless probability rules;
- pluggable scheduling algorithms: rr, wrr (weighted), lc (least
  connection), sh (source hash) — kube-proxy's --ipvs-scheduler;
- graceful termination: a backend removed from endpoints is first weighted
  to 0 (drains: existing connections keep flowing, new ones avoid it) and
  only deleted once its active connections hit zero — exactly the ipvs
  proxier's graceful-delete list (pkg/proxy/ipvs/graceful_termination.go);
- `dump()` renders `ipvsadm -ln` style output for operators.

Like the userspace mode, virtual servers are real listening sockets (the
portable stand-in for the kernel's hash table), so lc's connection counts
are real, not simulated.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..utils import faultline, locksan

SCHEDULERS = ("rr", "wrr", "lc", "sh")


class RealServer:
    """One backend of a virtual server (ipvs 'real server')."""

    __slots__ = ("addr", "weight", "active_conns", "total_conns")

    def __init__(self, addr: Tuple[str, int], weight: int = 1):
        self.addr = addr
        self.weight = weight
        self.active_conns = 0
        self.total_conns = 0


def _schedule(algo: str, backends: List[RealServer], client_ip: str,
              rr_state: List[int]) -> Optional[RealServer]:
    """Pick a backend.  Weight-0 backends (draining) are never picked."""
    eligible = [b for b in backends if b.weight > 0]
    if not eligible:
        return None
    if algo == "rr":
        rr_state[0] = (rr_state[0] + 1) % len(eligible)
        return eligible[rr_state[0]]
    if algo == "wrr":
        # expand by weight over a repeating cycle
        cycle = sum(b.weight for b in eligible)
        rr_state[0] = (rr_state[0] + 1) % cycle
        at = rr_state[0]
        for b in eligible:
            if at < b.weight:
                return b
            at -= b.weight
        return eligible[0]
    if algo == "lc":
        return min(eligible, key=lambda b: (b.active_conns, b.addr))
    if algo == "sh":
        h = int.from_bytes(
            hashlib.blake2s(client_ip.encode(), digest_size=4).digest(), "big")
        return eligible[h % len(eligible)]
    raise ValueError(f"unknown ipvs scheduler {algo!r}")


class VirtualServer:
    """A listening socket + scheduled real-server set (ipvs virtual svc)."""

    def __init__(self, listen_host: str, listen_port: int, algo: str):
        self.algo = algo
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((listen_host, listen_port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self.backends: List[RealServer] = []
        self._rr_state = [0]
        self._lock = locksan.make_lock("VirtualServer._lock")
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # ------------------------------------------------------------ backends

    def set_backends(self, addrs: List[Tuple[str, int]],
                     weights: Optional[Dict[Tuple[str, int], int]] = None):
        """Reconcile the real-server set.  Backends no longer in `addrs`
        are weighted to 0 and kept while they still carry connections
        (graceful termination); fully-drained ones are dropped."""
        weights = weights or {}
        with self._lock:
            have = {b.addr: b for b in self.backends}
            want = set(addrs)
            for addr in want:  # set: the same ip:port listed twice in the
                # endpoints must not become two real servers (double share)
                if addr in have:
                    have[addr].weight = weights.get(addr, 1)
                else:
                    b = RealServer(addr, weights.get(addr, 1))
                    self.backends.append(b)
                    have[addr] = b
            for b in self.backends:
                if b.addr not in want:
                    b.weight = 0  # drain
            self.backends = [
                b for b in self.backends
                if b.addr in want or b.active_conns > 0
            ]

    def pick(self, client_ip: str) -> Optional[RealServer]:
        with self._lock:
            return _schedule(self.algo, self.backends, client_ip,
                             self._rr_state)

    # ----------------------------------------------------------- data path

    def _accept_loop(self):
        while not self._closed:
            try:
                client, peer = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._splice, args=(client, peer[0]),
                             daemon=True).start()

    def _splice(self, client: socket.socket, client_ip: str):
        backend = self.pick(client_ip)
        if backend is None:
            client.close()
            return
        try:
            # same site as the userspace proxier: one spec faults BOTH
            # proxy modes' upstream legs
            faultline.check("proxy.upstream")
            upstream = socket.create_connection(backend.addr, timeout=10)
        except OSError:
            client.close()
            return
        with self._lock:
            backend.active_conns += 1
            backend.total_conns += 1
        upload_done = threading.Event()

        def pump(src, dst, done: Optional[threading.Event] = None):
            # half-close splice: EOF from src propagates as SHUT_WR on dst
            # only — shutting down both directions here would cut off the
            # response still flowing the other way
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                if done is not None:
                    done.set()
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        threading.Thread(target=pump, args=(client, upstream, upload_done),
                         daemon=True).start()
        pump(upstream, client)
        # grace for the client->upstream direction: set ONLY by its own
        # pump, so an early backend half-close doesn't truncate an upload
        upload_done.wait(1.0)
        client.close()
        upstream.close()
        with self._lock:
            backend.active_conns -= 1
            # a drained backend disappears once its last connection ends
            self.backends = [
                b for b in self.backends
                if b.weight > 0 or b.active_conns > 0
            ]

    def close(self):
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class IPVSProxier:
    """Service proxy in ipvs mode (kube-proxy --proxy-mode=ipvs analog):
    one VirtualServer per service port, scheduler per --ipvs-scheduler."""

    def __init__(self, clientset: Clientset,
                 factory: Optional[InformerFactory] = None,
                 scheduler: str = "rr", listen_host: str = "127.0.0.1"):
        if scheduler not in SCHEDULERS:
            raise ValueError(
                f"unknown ipvs scheduler {scheduler!r} (have {SCHEDULERS})")
        self.cs = clientset
        self.factory = factory or InformerFactory(clientset)
        self.scheduler = scheduler
        self.listen_host = listen_host
        self.services = self.factory.informer("services")
        self.endpoints = self.factory.informer("endpoints")
        # (ns, svc, port_name) -> VirtualServer
        self._virtuals: Dict[tuple, VirtualServer] = {}
        self._vip_index: Dict[tuple, tuple] = {}  # (clusterIP, port) -> key
        self._lock = locksan.make_lock("IPVSProxier._lock")
        self._dirty = threading.Event()
        self._stop = threading.Event()

    # ------------------------------------------------------------ lifecycle

    def start(self):
        for inf in (self.services, self.endpoints):
            inf.add_handler(on_add=lambda *_: self._dirty.set(),
                            on_update=lambda *_: self._dirty.set(),
                            on_delete=lambda *_: self._dirty.set())
        self.factory.start_all()
        self.factory.wait_for_sync()
        self._sync()
        threading.Thread(target=self._loop, daemon=True,
                         name="ipvs-sync").start()
        return self

    def stop(self):
        self._stop.set()
        self._dirty.set()
        with self._lock:
            for vs in self._virtuals.values():
                vs.close()
            self._virtuals.clear()
            self._vip_index.clear()
        self.factory.stop_all()

    def _loop(self):
        while not self._stop.is_set():
            self._dirty.wait(1.0)
            if self._stop.is_set():
                return
            if self._dirty.is_set():
                self._dirty.clear()
                try:
                    self._sync()
                except Exception:  # noqa: BLE001
                    import traceback

                    traceback.print_exc()

    # ----------------------------------------------------------------- sync

    @staticmethod
    def _subset_backends(ep, port_name: str):
        out = []
        for subset in ep.subsets:
            port = None
            for p in subset.ports:
                if not port_name or p.name == port_name:
                    port = p.port
                    break
            if port is None and subset.ports:
                # single-unnamed-port fallback, matching rules.py /
                # proxier.py: a named service port still routes to a
                # subset whose lone port carries no name
                port = subset.ports[0].port
            if port is None:
                continue
            out.extend((a.ip, port) for a in subset.addresses)
        return out

    def _sync(self):
        # one pass over the endpoints informer: per-port lookups below are
        # O(1), not a rescan of every Endpoints object (O(svc x eps) sync
        # would also stall resolve() behind the lock on big clusters)
        eps_by_key = {(ep.metadata.namespace, ep.metadata.name): ep
                      for ep in self.endpoints.list()}
        wanted = {}
        for svc in self.services.list():
            if not svc.spec.cluster_ip or svc.spec.cluster_ip == "None":
                continue
            for port in svc.spec.ports:
                key = (svc.metadata.namespace, svc.metadata.name, port.name)
                wanted[key] = (svc, port)
        with self._lock:
            for key in [k for k in self._virtuals if k not in wanted]:
                self._virtuals.pop(key).close()
            self._vip_index = {}
            for key, (svc, port) in wanted.items():
                vs = self._virtuals.get(key)
                if vs is None:
                    vs = VirtualServer(self.listen_host, 0, self.scheduler)
                    self._virtuals[key] = vs
                ep = eps_by_key.get(key[:2])
                vs.set_backends(
                    self._subset_backends(ep, key[2]) if ep else [])
                self._vip_index[(svc.spec.cluster_ip, port.port)] = key

    # ------------------------------------------------------------- routing

    def resolve(self, ip: str, port: int) -> Optional[Tuple[str, int]]:
        """ClusterIP:port -> local virtual-server address."""
        with self._lock:
            key = self._vip_index.get((ip, port))
            if key is None:
                return None
            return (self.listen_host, self._virtuals[key].port)

    def virtual_for(self, ns: str, name: str,
                    port_name: str = "") -> Optional[VirtualServer]:
        with self._lock:
            return self._virtuals.get((ns, name, port_name))

    def dump(self) -> str:
        """`ipvsadm -ln` style listing."""
        lines = ["IP Virtual Server (ktpu ipvs-mode analog)",
                 "Prot LocalAddress:Port Scheduler Flags",
                 "  -> RemoteAddress:Port  Weight ActiveConn TotalConn"]
        with self._lock:
            for (vip, port), key in sorted(self._vip_index.items()):
                vs = self._virtuals[key]
                lines.append(f"TCP  {vip}:{port} {vs.algo}")
                for b in vs.backends:
                    lines.append(
                        f"  -> {b.addr[0]}:{b.addr[1]}  "
                        f"{b.weight} {b.active_conns} {b.total_conns}")
        return "\n".join(lines) + "\n"
