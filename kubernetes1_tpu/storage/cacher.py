"""Watch cache: the apiserver's in-memory read layer over the MVCC store.

Ref: staging/src/k8s.io/apiserver/pkg/storage/cacher/cacher.go — upstream
funnels every GET/LIST/WATCH through an in-memory, watch-fed cache so the
backing store (etcd there; the in-process Store or a remote StoreServer
here) sees ONE watch and ONE list per apiserver instead of one per client,
and every read is answered from already-materialized state.  This module
is that layer:

- The cache is a revision-ordered window of encoded objects per
  collection; `list_raw`/`get_raw` serve the committed wire dicts without
  decoding anything — the HTTP layer pairs them with the scheme's
  once-per-revision serialization cache, so a read costs a dict lookup,
  not a decode+encode.
- Feeding has two modes, both BATCHED: one feed delivery = one group
  commit's worth of events, applied under ONE cache-lock acquisition with
  freshness advanced once per batch.  An IN-PROCESS Store feeds the cache
  synchronously from its commit path (`add_commit_hook`): the cache is
  never behind the store, reads are read-your-writes by construction, and
  there is no pump thread to wake per commit (a per-commit thread wakeup
  measured ~35% of write throughput on the GIL).  A REMOTE store
  (StoreServer over a socket) is fed the reference way: one internal
  watch (prefix "/registry/") drained by a pump thread.  `wait_fresh`
  blocks reads until the cache has caught up to a freshness target
  (cacher.go's waitUntilFreshAndBlock); with a stream that carries
  progress revisions on its heartbeats (StoreServer watches — the etcd
  progress-notify analog) the target comes from the highest revision this
  apiserver's RemoteStore has OBSERVED in responses, so reads are
  read-your-writes for writes through this apiserver and progress-bounded
  for peers' writes WITHOUT a current_revision round-trip per GET/LIST
  (upstream's consistent-list-from-cache semantics).  Feeds without
  progress support keep the strict current_revision target.
  `CacheNotReady` sends callers to the authoritative store path.
- Watches resume from the cache's own history window; resuming below the
  floor raises TooOldResourceVersion (HTTP 410 upstairs) and the client
  relists.  Slow consumers are EVICTED through the bounded Watcher queue —
  the same 410-relist path — so one wedged client cannot pin the event
  backlog for everyone.
- If a watch feed dies (remote store restart/failover), the cacher
  RESEEDS from a fresh list and evicts every open watcher to relist:
  correctness over continuity, the cacher.go
  terminateAllWatchers-on-storage-error behavior.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ..machinery import DELETED, TooOldResourceVersion, WatchEvent
from ..utils import invariants, locksan, mutsan, schedsan
from ..utils.metrics import Histogram
from .store import (
    DEFAULT_WATCH_QUEUE_LIMIT,
    Watcher,
    collection_of as _collection_of,
    history_index,
)

# The cache's resume window.  Smaller than the store's ring: an evicted or
# long-gone watcher relists against the CACHE (cheap), so a deep window
# buys little here.
DEFAULT_CACHER_HISTORY_LIMIT = 16384

# ------------------------------------------------------------ selector indexes
#
# Declared field-selector indexes (ref: cacher.go's storage.IndexerFuncs —
# upstream indexes pods by spec.nodeName so a kubelet's LIST is O(its
# pods), not O(all pods)).  Registration is MODULE-LEVEL and happens at
# import: every cacher in the process (and every apiserver over the same
# store) maintains the identical index set, so routing a LIST through any
# peer gives the same complexity.  The invariant the design rests on:
# indexed collections update their index in the SAME critical section as
# the cache apply (_apply_batch_locked under _cond), so an index lookup
# can never observe a key the data map doesn't (or vice versa).
#
# The index is a pure CANDIDATE NARROWING: readers re-check the full
# selector on the bucket's entries, so a registered extractor that ever
# disagreed with the registry's field matcher could cost false positives
# (filtered out) but correctness never depends on parity — only the
# no-false-NEGATIVES property, which holds because both sides read the
# same dotted wire path with the same default.
_SELECTOR_INDEXES: Dict[str, Dict[str, str]] = {}


def register_selector_index(resource: str, field: str, default: str = ""):
    """Declare `field` (dotted wire path, e.g. "spec.nodeName") indexed
    for `resource`.  `default` is the bucket value for objects missing
    the field — it must match the registry's field-selector default for
    the same (resource, field) or indexed lookups under-report."""
    _SELECTOR_INDEXES.setdefault(resource, {})[field] = default


def selector_indexes(resource: str) -> Dict[str, str]:
    """field -> missing-value default for the resource ({} = unindexed)."""
    return _SELECTOR_INDEXES.get(resource, {})


def index_value(d: Dict[str, Any], field: str, default: str = "") -> str:
    """Extract the indexed field's bucket value from an encoded wire dict
    (dotted camelCase path; missing -> default).  Mirrors the registry's
    field_get walk for plain (non-defaulted) fields."""
    cur: Any = d
    for part in field.split("."):
        if not isinstance(cur, dict):
            cur = None
            break
        cur = cur.get(part)
    return default if cur is None else str(cur)


# the mandatory index: at 150k pods a kubelet's spec.nodeName LIST must
# be O(its pods) — the k8s cacher precedent this module cites above
register_selector_index("pods", "spec.nodeName")


class CacheNotReady(Exception):
    """The cache cannot answer a fresh read right now (still seeding, or
    the pump fell behind past the freshness deadline); callers fall back
    to the authoritative store path."""


def key_for_dict(scheme, d: Dict[str, Any]) -> Optional[str]:
    """Reconstruct the registry storage key for an encoded object — remote
    watch events carry objects, not keys.  Mirrors Registry.key's layout:
    /registry/<plural>[/<namespace>]/<name>."""
    plural = scheme.resource_of.get(d.get("kind", ""))
    meta = d.get("metadata") or {}
    name = meta.get("name", "")
    if not plural or not name:
        return None
    if scheme.namespaced.get(plural, True):
        return f"/registry/{plural}/{meta.get('namespace') or 'default'}/{name}"
    return f"/registry/{plural}/{name}"


class Cacher:
    """In-memory, revision-ordered view of one store."""

    # Registry.watch probes this before passing an index_hint: only the
    # watch-cache layers (Cacher/ShardedCacher) bucket watchers; the
    # authoritative store keeps the scan fan-out.
    dispatch_index_capable = True

    def __init__(self, store, scheme, prefix: str = "/registry/",
                 history_limit: int = DEFAULT_CACHER_HISTORY_LIMIT,
                 queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT,
                 fresh_timeout: float = 5.0,
                 force_watch_feed: bool = False):
        self._store = store
        self._scheme = scheme
        self._prefix = prefix
        self._history_limit = history_limit
        self._queue_limit = queue_limit
        self._fresh_timeout = fresh_timeout
        # one condition guards the whole view; pump-mode readers wait on
        # it for freshness and the feed notifies per applied revision
        self._cond = locksan.make_condition(name="storage.Cacher._cond")
        self._data: Dict[str, Tuple[int, Dict[str, Any]]] = {}
        self._by_collection: Dict[str, set] = {}
        # secondary selector indexes (guarded by _cond, updated in the
        # same critical section as the data map — see module docstring):
        # collection -> field -> value -> set(keys)
        self._indexes: Dict[str, Dict[str, Dict[str, set]]] = {}
        self._history: List[Tuple[int, str, str, Dict[str, Any]]] = []
        self._rev = 0
        self._compacted_rev = 0
        self._watchers: List[Watcher] = []
        # WATCH DISPATCH INDEX (guarded by _cond, maintained in the same
        # critical section as registration/removal): watchers that
        # presented an `=` requirement on a DECLARED selector index are
        # bucketed by (collection, field) -> value; everyone else is on
        # the scan list.  The commit fan-out walks only the buckets named
        # by each event's old+new indexed values plus the scan list, so
        # delivery work is O(interested watchers), not O(watchers) —
        # 5000 kubelet watchers cost ~1 bucket lookup per pod event
        # instead of 5000 selector tests.  The index only NARROWS: the
        # serving layer still re-checks event_matches on every delivered
        # event, so an indexed stream's frames equal the scan stream's
        # by construction (the PR 12 list-index invariant, applied to
        # dispatch).
        self._watch_index: Dict[Tuple[str, str], Dict[str, List[Watcher]]] = {}
        self._scan_watchers: List[Watcher] = []
        # dispatch economics (under _cond): indexed_hits = deliveries
        # routed through a bucket; scans = (event x scan-watcher) pairs
        # walked on the legacy leg.  hits + scans IS the fan-out work.
        self.dispatch_indexed_hits = 0
        self.dispatch_scans = 0
        # sync mode: commits that fired between hook registration and the
        # seed list buffer here (None once seeded)
        self._pending_records: Optional[List[tuple]] = []
        self._ready = threading.Event()
        self._stopping = threading.Event()
        self._feed = None
        self._sync = (hasattr(store, "add_commit_hook")
                      and not force_watch_feed)
        # pump mode: True once the feed proves it carries progress
        # revisions on heartbeats (RemoteWatcher) — wait_fresh then skips
        # the per-read current_revision RPC
        self._stream_progress = False
        self.reseeds = 0
        self.watch_evictions = 0
        # fan-out coalescing economics (mutated under _cond): one wakeup
        # may deliver a whole batch — wakeups/events < 1.0 under burst
        self.watch_wakeups = 0
        self.watch_events = 0
        # eviction can fire from a replay thread that holds no cache lock
        self._evict_lock = locksan.make_lock("storage.Cacher._evict_lock")
        self._thread: Optional[threading.Thread] = None
        # freshness-wait lag (obs plane, rendered on the apiserver's
        # /metrics): how long reads block in wait_fresh for the cache to
        # catch the store.  Sync-fed caches are fresh by construction and
        # never observe (zero-cost on the hot read path); only pump-mode
        # waits land here.
        self.freshness_wait_seconds = Histogram(
            "ktpu_cacher_freshness_wait_seconds",
            "time LIST/GET reads waited for watch-cache freshness",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Cacher":
        if self._sync:
            # hook FIRST so no commit is missed; the seed then applies any
            # records that raced in between hook and list
            self._store.add_commit_hook(self._on_commit_batch)
            entries, rev = self._store.list_raw(self._prefix)
            self._seed(entries, rev)
            self._ready.set()
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cacher-pump")
        self._thread.start()
        return self

    def stop(self):
        self._stopping.set()
        if self._sync:
            self._store.remove_commit_hook(self._on_commit_batch)
        feed = self._feed
        if feed is not None:
            feed.stop()
        with self._cond:
            watchers, self._watchers = self._watchers, []
            self._watch_index = {}
            self._scan_watchers = []
            self._cond.notify_all()
        for w in watchers:
            w.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _note_watch_eviction(self):
        with self._evict_lock:
            self.watch_evictions += 1

    def _remove_watcher(self, w: Watcher):
        with self._cond:
            self._unregister_watcher_locked(w)

    def _unregister_watcher_locked(self, w: Watcher):
        """Must hold _cond: drop the watcher from the master list AND its
        dispatch route (bucket or scan list) — a bucket entry that
        outlived its watcher would keep paying a (dead) delivery per
        matching event forever."""
        try:
            self._watchers.remove(w)
        except ValueError:
            return  # already unregistered (reseed swept it, racing stop)
        hint = getattr(w, "dispatch_hint", None)
        if hint is None:
            try:
                self._scan_watchers.remove(w)
            except ValueError:
                pass
            return
        coll, field, value = hint
        buckets = self._watch_index.get((coll, field))
        if buckets is None:
            return
        bucket = buckets.get(value)
        if bucket is None:
            return
        try:
            bucket.remove(w)
        except ValueError:
            return
        if not bucket:
            del buckets[value]
        if not buckets:
            del self._watch_index[(coll, field)]

    # ------------------------------------------------------------- feeding

    def _seed(self, entries, rev: int) -> List[Watcher]:
        with self._cond:
            stale, self._watchers = self._watchers, []
            self._watch_index = {}
            self._scan_watchers = []
            if self._ready.is_set():
                self.reseeds += 1
            self._data = {key: (r, obj) for key, r, obj in entries}
            self._by_collection = {}
            self._indexes = {}
            for key, (_r, obj) in self._data.items():
                coll = _collection_of(key)
                self._by_collection.setdefault(coll, set()).add(key)
                self._index_add_locked(coll, key, obj)
            self._history = []
            self._rev = rev
            self._compacted_rev = rev
            pending, self._pending_records = self._pending_records, None
            raced = [r for r in (pending or ()) if r[0] > rev]
            if raced:
                self._apply_batch_locked(raced)
            self._cond.notify_all()
        return stale

    def _on_commit_batch(self, records: List[tuple]):
        """Synchronous sink: runs inside the store's commit critical
        section with one GROUP COMMIT's records, so the cache is fresh the
        moment the write returns — one cache-lock acquisition, one
        freshness advance, one wakeup per watcher for the whole batch."""
        records = [r for r in records if r[2].startswith(self._prefix)]
        if not records:
            return
        # the commit->apply window: a registered watcher must never miss
        # an event that lands here while its registration is in flight
        schedsan.preempt("cacher.apply")
        with self._cond:
            if self._pending_records is not None:  # hook beat the seed
                self._pending_records.extend(records)
                return
            self._apply_batch_locked(records)
            self._cond.notify_all()

    def _apply_batch_locked(self, records: List[tuple]):
        """Must hold _cond: fold one batch into the view and fan out with
        ONE push per interested watcher (events shared across watchers).
        Callers notify _cond once per batch.

        Dispatch is INDEX-ROUTED: each event walks only the buckets named
        by its old and new indexed values (BOTH — an update that moves
        the value is a transition both sides' streams must see, so their
        frames stay equal to a scan stream's after the serving layer's
        event_matches re-check) plus the scan list.  Bucket updates for
        the DATA index and deliveries through the WATCH index happen in
        this same critical section, so a registered watcher can never
        miss an event between its registration and the next apply."""
        deliveries: Dict[Watcher, List[WatchEvent]] = {}
        scan = self._scan_watchers
        # sanitizer-build probe: capture per-event index transitions so
        # the both-buckets rule can be re-checked independently below
        # (against each watcher's stamped dispatch_hint, NOT the bucket
        # maps the dispatch loop consults)
        probe_evs = [] if invariants.armed() else None
        for rev, typ, key, obj in records:
            coll = _collection_of(key)
            old_obj: Optional[Dict[str, Any]] = None
            if typ == DELETED:
                old = self._data.pop(key, None)
                keys = self._by_collection.get(coll)
                if keys is not None:
                    keys.discard(key)
                if old is not None:
                    old_obj = old[1]
                    self._index_remove_locked(coll, key, old_obj)
            else:
                old = self._data.get(key)
                old_obj = None if old is None else old[1]
                self._data[key] = (rev, obj)
                self._by_collection.setdefault(coll, set()).add(key)
                self._index_update_locked(coll, key, old_obj, obj)
            self._history.append((rev, typ, key, obj))
            if rev > self._rev:
                self._rev = rev
            ev = WatchEvent(typ, obj)
            if probe_evs is not None:
                specs = _SELECTOR_INDEXES.get(coll) or {}
                field_vals = {}
                for field, default in specs.items():
                    vals = {index_value(obj, field, default)}
                    if old_obj is not None:
                        vals.add(index_value(old_obj, field, default))
                    field_vals[field] = vals
                probe_evs.append((key, coll, ev, field_vals))
            if scan:
                self.dispatch_scans += len(scan)
                for w in scan:
                    if key.startswith(w.prefix):
                        deliveries.setdefault(w, []).append(ev)
            specs = _SELECTOR_INDEXES.get(coll)
            if specs:
                for field, default in specs.items():
                    buckets = self._watch_index.get((coll, field))
                    if not buckets:
                        continue
                    vals = {index_value(obj, field, default)}
                    if old_obj is not None:
                        vals.add(index_value(old_obj, field, default))
                    for v in vals:
                        for w in buckets.get(v, ()):
                            if key.startswith(w.prefix):
                                self.dispatch_indexed_hits += 1
                                deliveries.setdefault(w, []).append(ev)
        if len(self._history) > self._history_limit:
            drop = len(self._history) - self._history_limit
            self._compacted_rev = self._history[drop - 1][0]
            del self._history[:drop]
        if probe_evs is not None:
            invariants.rev_monotonic("cacher.apply",
                                     invariants.stream_of(self, "cacher"),
                                     records[0][0])
            for key, coll, ev, field_vals in probe_evs:
                expected = []
                for w in self._watchers:
                    if not key.startswith(w.prefix):
                        continue
                    hint = getattr(w, "dispatch_hint", None)
                    if hint is None:
                        must = w in scan
                    else:
                        hcoll, hfield, hval = hint
                        must = (hcoll == coll
                                and hval in field_vals.get(hfield, ()))
                    if must:
                        expected.append(w)
                delivered = [w for w, evs in deliveries.items()
                             if any(x is ev for x in evs)]
                invariants.dispatch_superset(
                    "cacher.dispatch", expected, delivered)
        evicted = False
        for w, evs in deliveries.items():
            w._push_batch(evs)
            self.watch_wakeups += 1
            self.watch_events += len(evs)
            evicted = evicted or w.evicted
        if evicted:
            for w in [x for x in self._watchers if x.evicted]:
                self._unregister_watcher_locked(w)

    # ------------------------------------------------------------- indexes

    def _index_add_locked(self, coll: str, key: str, obj: Dict[str, Any]):
        specs = _SELECTOR_INDEXES.get(coll)
        if not specs:
            return
        fields = self._indexes.setdefault(coll, {})
        for field, default in specs.items():
            fields.setdefault(field, {}).setdefault(
                index_value(obj, field, default), set()).add(key)

    def _index_remove_locked(self, coll: str, key: str, obj: Dict[str, Any]):
        specs = _SELECTOR_INDEXES.get(coll)
        if not specs:
            return
        fields = self._indexes.get(coll)
        if fields is None:
            return
        for field, default in specs.items():
            buckets = fields.get(field)
            if buckets is None:
                continue
            val = index_value(obj, field, default)
            bucket = buckets.get(val)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del buckets[val]

    def _index_update_locked(self, coll: str, key: str,
                             old: Optional[Dict[str, Any]],
                             new: Dict[str, Any]):
        specs = _SELECTOR_INDEXES.get(coll)
        if not specs:
            return
        fields = self._indexes.setdefault(coll, {})
        for field, default in specs.items():
            newv = index_value(new, field, default)
            buckets = fields.setdefault(field, {})
            if old is not None:
                oldv = index_value(old, field, default)
                if oldv == newv:
                    continue  # unchanged: the common status-update case
                bucket = buckets.get(oldv)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del buckets[oldv]
            buckets.setdefault(newv, set()).add(key)

    # ------------------------------------------------- pump (remote store)

    def _run(self):
        while not self._stopping.is_set():
            try:
                entries, rev = self._store.list_raw(self._prefix)
                feed = self._store.watch(self._prefix, since_rev=rev,
                                         queue_limit=0)
            except TooOldResourceVersion:
                continue  # raced a compaction between list and watch
            except Exception:  # noqa: BLE001 — pump must outlive store blips
                traceback.print_exc()
                if self._stopping.wait(0.5):
                    return
                continue
            self._feed = feed
            # a feed that carries progress revisions (RemoteWatcher over a
            # StoreServer stream) lets wait_fresh go RPC-free
            self._stream_progress = hasattr(feed, "progress_rev")
            stale = self._seed(entries, rev)
            for w in stale:
                # watchers from the previous epoch may have a gap: 410
                # them so their reflectors relist against the fresh view.
                # note=False: these are reseed casualties, not slow
                # consumers — the `reseeds` counter tracks the cause
                w._evict(note=False)
            self._ready.set()
            while not self._stopping.is_set():
                evs = feed.next_batch_timeout(1.0)
                if evs is None:
                    if feed._stopped.is_set() or getattr(feed, "closed", False):
                        break  # upstream ended: reseed
                    continue
                if not evs:
                    # progress-only wakeup: the stream proved the store is
                    # at progress_rev with nothing in flight — advance
                    # freshness so waiters unblock without an event
                    self._note_progress(getattr(feed, "progress_rev", 0))
                    continue
                if not self._apply_batch(evs):
                    break  # unmappable event (unknown kind): reseed
            feed.stop()
            if not self._stopping.is_set():
                self._stopping.wait(0.05)  # tiny backoff between reseeds

    def _note_progress(self, rev: int):
        if not rev:
            return
        with self._cond:
            if rev > self._rev:
                self._rev = rev
                self._cond.notify_all()

    def _apply_batch(self, evs: List[WatchEvent]) -> bool:
        """Pump-side: fold a batch of remote watch events (no key on the
        wire) under ONE cache-lock acquisition.  Returns False when an
        event cannot be mapped to a key — a kind this scheme doesn't know
        yet (CRD racing its registration on a peer apiserver).  Silently
        dropping it would leave a permanent hole in the view and stall
        freshness; the pump reseeds instead — the seed path ships keys
        verbatim, so it is kind-agnostic."""
        records = []
        for ev in evs:
            d = ev.object
            meta = d.get("metadata") or {}
            try:
                rev = int(meta.get("resourceVersion") or 0)
            except (TypeError, ValueError):
                continue  # malformed event: ignore, don't reseed-loop
            if not rev:
                continue
            key = key_for_dict(self._scheme, d)
            if key is None:
                return False
            records.append((rev, ev.type, key, d))
        if records:
            schedsan.preempt("cacher.apply")
            with self._cond:
                self._apply_batch_locked(records)
                self._cond.notify_all()
        return True

    # ---------------------------------------------------------------- reads

    def wait_fresh(self, timeout: Optional[float] = None):
        """Block until the cache covers every revision the store had
        committed when this call started (read-your-writes; ref cacher.go
        waitUntilFreshAndBlock).  Synchronous feeding is fresh by
        construction — the hook runs inside the commit critical section —
        so only pump mode ever waits.  Raises CacheNotReady past the
        deadline."""
        timeout = self._fresh_timeout if timeout is None else timeout
        if not self._ready.wait(timeout):
            raise CacheNotReady("watch cache not seeded yet")
        if self._sync:
            return
        t0 = time.monotonic()
        seen = getattr(self._store, "last_seen_revision", None)
        if self._stream_progress and seen is not None:
            # RPC-free freshness (the etcd progress-notify analog): the
            # target is the highest revision THIS apiserver's store client
            # has observed in any response — strict read-your-writes for
            # writes through this apiserver; peers' writes are bounded by
            # stream latency plus the progress heartbeat, the same
            # staleness upstream's watch-cache reads carry.
            target = seen()
        else:
            # no progress on this stream: strict freshness via one
            # current_revision round-trip per read (cheap for an
            # in-process store in forced-pump mode, the only such feed)
            target = self._store.current_revision()
        try:
            self._wait_rev_locked_entry(target, timeout)
        finally:
            # observe on the CacheNotReady path too: the timeout-length
            # stalls are exactly the tail this SLI exists to surface
            self.freshness_wait_seconds.observe(time.monotonic() - t0)

    def _wait_rev_locked_entry(self, target: int, timeout: float):
        """Block until the cache has applied revision `target`."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._rev < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CacheNotReady(
                        f"cache at rev {self._rev}, store at {target}")
                self._cond.wait(remaining)

    def list_raw(self, prefix: str) -> Tuple[List[Tuple[str, int, Dict[str, Any]]], int]:
        """Fresh (key, rev, encoded obj) entries under prefix + the cache
        revision (== a store revision at least as new as every write
        acknowledged before this call)."""
        self.wait_fresh()
        # handouts are SHARED with the cache, the store's history ring and
        # the serialization cache keyed on their resourceVersion: freeze
        # them (sanitizer on, i.e. tests) so an in-place mutation cannot
        # silently diverge live state from already-cached bytes.  The
        # enabled() check is hoisted OUT of the loop: this is the 2000-pod
        # LIST hot path, inside the lock the commit feed contends on —
        # production must pay zero per-entry sanitizer cost
        frozen = mutsan.enabled()
        with self._cond:
            keys = self._by_collection.get(_collection_of(prefix))
            if not keys:
                return [], self._rev
            entries = []
            for key in sorted(keys):
                if not key.startswith(prefix):
                    continue
                ent = self._data.get(key)
                if ent is None:
                    continue
                obj = mutsan.freeze(ent[1], "Cacher.list_raw") if frozen \
                    else ent[1]
                entries.append((key, ent[0], obj))
            return entries, self._rev

    def list_raw_indexed(self, prefix: str, field: str, value: str):
        """Fresh (key, rev, obj) entries under prefix whose indexed
        `field` extracts to `value`, plus the cache revision — the
        O(its pods) answer to a kubelet's spec.nodeName LIST.  Returns
        None when no such index is declared for the collection (callers
        fall back to the full scan), so an unindexed selector keeps
        today's path untouched."""
        coll = _collection_of(prefix)
        if field not in _SELECTOR_INDEXES.get(coll, {}):
            return None
        self.wait_fresh()
        frozen = mutsan.enabled()
        with self._cond:
            bucket = self._indexes.get(coll, {}).get(field, {}).get(value)
            if not bucket:
                return [], self._rev
            entries = []
            for key in sorted(bucket):
                if not key.startswith(prefix):
                    continue  # namespace-scoped LIST over a collection index
                ent = self._data.get(key)
                if ent is None:
                    continue
                obj = mutsan.freeze(ent[1], "Cacher.list_raw_indexed") \
                    if frozen else ent[1]
                entries.append((key, ent[0], obj))
            return entries, self._rev

    def get_raw(self, key: str) -> Optional[Dict[str, Any]]:
        """Fresh encoded wire dict for one key; None when absent."""
        self.wait_fresh()
        with self._cond:
            ent = self._data.get(key)
            # frozen: shared with the cache and the serialized-bytes cache
            return None if ent is None else mutsan.freeze(
                ent[1], "Cacher.get_raw")

    def compacted_revisions(self) -> List[int]:
        """Per-shard history floors (one element here; ShardedCacher
        returns N).  A continue token whose resume revision fell below
        the floor can no longer anchor a gap-free relist+watch: the
        server answers 410 and the client restarts cleanly."""
        with self._cond:
            return [self._compacted_rev]

    # ---------------------------------------------------------------- watch

    def watch(self, prefix: str, since_rev: int = 0,
              queue_limit: Optional[int] = None,
              index_hint: Optional[Tuple[str, str]] = None) -> Watcher:
        """Watch prefix from the cache's history window.  Resuming returns
        EXACTLY the events with rev > since_rev (waiting for the cache to
        catch up to the store first, so a resume at a store-fresh revision
        never sees duplicates); resuming below the window floor raises
        TooOldResourceVersion and the client relists.

        index_hint=(field, value) — the watcher's selector carries an
        equality requirement on `field`: if the prefix's collection
        declares that field indexed, the watcher is bucketed so the
        commit fan-out routes it only events whose old or new `field`
        extracts to `value` (a strict superset of what event_matches
        passes, so the serving layer's re-check keeps frames identical
        to a scan stream's).  Undeclared fields fall back to the scan
        list — the hint can only narrow, never lose."""
        limit = self._queue_limit if queue_limit is None else queue_limit
        self.wait_fresh()
        if since_rev:
            # the client PROVED since_rev exists by presenting it (a list
            # rv, a write response) — in progress-tracked pump mode the
            # wait_fresh target can lag a PEER apiserver's write, and
            # registering below since_rev would replay rev <= since_rev
            # events as duplicates when the stream catches up
            self._wait_rev_locked_entry(since_rev, self._fresh_timeout)
        w = Watcher(self, prefix, queue_limit=limit,
                    buffering=bool(since_rev))
        replay = self.attach_watcher(w, since_rev, index_hint=index_hint)
        if since_rev:
            w._replay_and_go_live(replay)
        return w

    def attach_watcher(self, w: Watcher, since_rev: int = 0,
                       index_hint: Optional[Tuple[str, str]] = None):
        """Register an externally-built Watcher against this cache's view
        (the sharded fan-in path — one Watcher shared across N per-shard
        cachers) and return the history slice the caller must replay
        outside the lock.  The caller owns the freshness waits
        (wait_fresh / _wait_rev_locked_entry) that Cacher.watch performs
        before registering."""
        with self._cond:
            if since_rev and since_rev < self._compacted_rev:
                raise TooOldResourceVersion(
                    f"revision {since_rev} compacted "
                    f"(floor {self._compacted_rev})")
            replay = (self._history[history_index(self._history, since_rev):]
                      if since_rev else [])
            self._watchers.append(w)
            self._register_dispatch_locked(w, index_hint)
        return replay

    def _register_dispatch_locked(self, w: Watcher,
                                  index_hint: Optional[Tuple[str, str]]):
        """Must hold _cond: route the watcher into its dispatch bucket
        (declared index + equality hint) or onto the scan list.  The
        route is stamped on the watcher (dispatch_hint) so removal can
        undo exactly this registration; a FanInWatcher attached to N
        shard cachers gets the same stamp from each — same (coll, field,
        value) triple, per-cacher bucket membership."""
        coll = _collection_of(w.prefix)
        if index_hint:
            field, value = index_hint
            if field in _SELECTOR_INDEXES.get(coll, {}):
                value = str(value)
                w.dispatch_hint = (coll, field, value)
                self._watch_index.setdefault(
                    (coll, field), {}).setdefault(value, []).append(w)
                return
        w.dispatch_hint = None
        self._scan_watchers.append(w)

    def current_cached_revision(self) -> int:
        """The cache's applied revision right now (the fan-in facade
        seeds from-now resume positions with it)."""
        with self._cond:
            return self._rev
