"""StoreServer: the L0 store behind its own socket — the etcd role.

Ref: the reference's L0 is a separately-clustered etcd behind N stateless
apiservers (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:152,263
— every apiserver is just an etcd client).  Splitting the MVCC store into
its own process gives this framework the same shape: the store process is
the single source of truth and any number of apiservers (each running the
full authn/admission/REST stack) serve one cluster, with leader-elected
controllers/schedulers behind them.  Control-plane HA then means "kill any
apiserver; clients fail over; nothing is lost" — the store's WAL covers
store-process restarts.

Wire protocol (newline-JSON over AF_UNIX or TCP, optionally TLS):
  request:  {"id": N, "method": "...", "params": {...}}\n
  response: {"id": N, "result": ...} | {"id": N, "error": {"kind","msg"}}\n
A `watch` request commits its CONNECTION to streaming: after the ack, the
server pushes {"event": {"type", "object"}} frames — or, when a group
commit delivered several at once, ONE {"events": [{"type", "object"},
...]} frame (one socket write+flush and one client-side queue wakeup per
batch) — until either side closes.  Heartbeats are {"progress": {"rev":
N}} frames stamping the store revision (the etcd progress-notify /
watch-bookmark analog: the client's cacher tracks freshness from the
stream instead of polling current_revision); blank lines remain accepted
as legacy heartbeats.  Objects cross as their encoded dict form — the
scheme lives in the clients.

The `commit_batch` method ships N mutations in one RPC and one store
group commit ({"ops": [{"op", "key", "obj"?, "expect_rv"?}, ...]} ->
{"results": [{"obj": ...} | {"error": ...}, ...]}); `get_many` is its
read half.

Why not raft here: etcd's quorum is WHY the reference gets store HA for
free, but a correct raft is a project of its own.  This server + WAL gives
apiserver-level HA now (the VERDICT r3 bar: survive apiserver death) and
keeps L0 behind one interface a raft group could replace later.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
import traceback
from typing import Optional, Tuple, Union

from ..machinery import (
    AlreadyExists,
    ApiError,
    Conflict,
    NotFound,
    TooOldResourceVersion,
)
from . import wire
from .store import Store
from ..machinery.codec import CodecError, known_codecs
from ..utils import faultline, locksan

class NotPrimary(ApiError):
    """Raised by a standby store for any client operation before promotion.
    The client (RemoteStore) treats it as 'try the next server' — the
    request was definitely NOT applied, so failover-retry is always safe."""


class ReplicationUnavailable(ApiError):
    """Durable ack policy: replication cannot currently protect this
    answer (standby absent or lagging past the ack timeout), so the write
    is NOT acknowledged — it may or may not be durable, and the client's
    transient-retry policy (503) re-asks until the standby catches up.
    This is the etcd no-quorum answer: fail the write, never ack a
    revision a primary death could take with it."""

    code = 503
    reason = "ServiceUnavailable"


_ERROR_KINDS = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "TooOldResourceVersion": TooOldResourceVersion,
    "NotPrimary": NotPrimary,
    "Unavailable": ReplicationUnavailable,
}

WATCH_HEARTBEAT_SECONDS = 5.0
# How long a write waits for the standby's ack before the standby is
# declared a laggard and dropped (availability over a stuck replica —
# the dropped standby reconnects and resyncs; the un-replicated window
# is logged).  See StoreServer._await_replication.
REPLICATION_ACK_TIMEOUT_SECONDS = 2.0


def error_to_wire(e: Exception) -> dict:
    for kind, cls in _ERROR_KINDS.items():
        if isinstance(e, cls):
            return {"kind": kind, "msg": str(e)}
    return {"kind": "Internal", "msg": f"{type(e).__name__}: {e}"}


def error_from_wire(err: dict) -> Exception:
    cls = _ERROR_KINDS.get(err.get("kind", ""), ApiError)
    return cls(err.get("msg", "store error"))


class StoreServer:
    """Serves a Store over a unix or TCP socket.  The store's scheme is
    only used for encode/decode at the edges; the server deals in the
    encoded dict representation throughout (no double decode)."""

    def __init__(self, store: Store, address: Union[str, Tuple[str, int]],
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = "", primary: bool = True,
                 repl_ack_policy: str = "available"):
        """The store IS the cluster — its socket must never be an
        unauthenticated bypass of the apiserver's authz stack.  Unix
        sockets are chmod 0600 (same-user only, the etcd-on-localhost
        posture); TCP mode with client_ca_file REQUIRES a client cert
        signed by that CA (etcd's peer/client mTLS).

        primary=False serves a warm standby: every client operation
        answers NotPrimary (so RemoteStore fails over to the real primary)
        until promote() flips it live."""
        self.store = store
        self.primary = primary
        self._threads = []
        self._stop = threading.Event()
        # every ACCEPTED connection, so stop() can sever them: closing
        # only the listener left established connections serving (and
        # ACKING WRITES on) a closed store — an in-process split brain the
        # chaos suite caught; a killed process severs everything, so stop
        # must too
        self._conns: set = set()
        self._conns_lock = locksan.make_lock("StoreServer._conns_lock")
        # replication: feed -> last acked rev, guarded by _repl_cond
        self._repl_cond = locksan.make_condition(name="StoreServer._repl_cond")
        self._replica_acks: dict = {}
        # Once a standby has EVER attached, write acks keep waiting for
        # one even across link flaps (see _await_replication): without
        # this, every write landing in a reconnect-resync window would be
        # silently unprotected, and a primary death mid-flap would lose
        # acknowledged writes — the chaos suite's repl-sever + kill
        # schedule found exactly that.  Guarded by _repl_cond.
        self._expect_replicas = False
        # sticky: has ANY standby ever attached?  Distinguishes "never
        # configured replication" (unprotected is meaningless — nothing
        # counts) from "standby died" (every ack until one reattaches is
        # real exposure and counts).  Guarded by _repl_cond.
        self._ever_attached = False
        self.unprotected_acks = 0
        # "available" (default): an ack-gate timeout counts + logs an
        # UNPROTECTED ack and availability wins — the 2-member tradeoff
        # tier-1's laggard contract codifies.  "durable": a timeout FAILS
        # the request with ReplicationUnavailable instead (503, client
        # retries); no client-visible answer ever outruns the standby, so
        # a primary kill cannot lose an acknowledged write — the chaos
        # suite's repl-sever + kill schedules run in this mode.
        if repl_ack_policy not in ("available", "durable"):
            raise ValueError(
                f"repl_ack_policy must be 'available' or 'durable', "
                f"got {repl_ack_policy!r}")
        self.repl_ack_policy = repl_ack_policy
        if primary and repl_ack_policy == "durable":
            # durable has no boot window: writes accepted before the
            # standby's FIRST attach must wait for it (or fail 503) —
            # arming lazily on attach let pre-attach writes ack with zero
            # replication, exactly the loss the policy forbids.  A
            # PROMOTED standby (primary=False here) keeps the lazy arm:
            # with two members, post-failover writes proceeding alone is
            # the documented tradeoff.
            self._expect_replicas = True
        if isinstance(address, str):
            try:
                os.unlink(address)
            except FileNotFoundError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(address)
            os.chmod(address, 0o600)
            self.address: Union[str, Tuple[str, int]] = address
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind(address)
            self.address = self._sock.getsockname()[:2]
        if tls_cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert_file,
                                keyfile=tls_key_file or None)
            if client_ca_file:
                ctx.load_verify_locations(cafile=client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._sock = ctx.wrap_socket(self._sock, server_side=True,
                                         do_handshake_on_connect=False)
        self._sock.listen(64)

    def start(self) -> "StoreServer":
        from ..utils.gctune import tune_for_server

        tune_for_server()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="store-server")
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            # shutdown, not just close: per-connection threads blocked in
            # a read must see EOF NOW, and their clients must observe a
            # dead server — not a half-alive one that still answers
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self.store.close()

    # ----------------------------------------------------------------- serve

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._conns_lock:
                if self._stop.is_set():
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn):
        handshake = getattr(conn, "do_handshake", None)
        try:
            if handshake is not None:
                handshake()
        except (OSError, ValueError):
            self._drop_conn(conn)
            return
        f = conn.makefile("rwb")
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except ValueError:
                    break
                rid = req.get("id")
                method = req.get("method")
                params = req.get("params") or {}
                if method == wire.NEGOTIATE_METHOD:
                    # connection-level codec/framing upgrade: answered even
                    # by a standby (the NotPrimary verdict belongs to the
                    # OPERATIONS that follow, not to the transport).  An
                    # unsupported codec answers an error and the connection
                    # STAYS newline-JSON — the client's fallback path.
                    codec_id = params.get("codec", "")
                    framing = params.get("framing", "")
                    if (codec_id in known_codecs()
                            and framing == wire.FRAMING_LP1):
                        f.write(json.dumps({"id": rid, "result": {
                            "codec": codec_id,
                            "framing": wire.FRAMING_LP1}}).encode() + b"\n")
                        f.flush()
                        self._serve_conn_binary(conn, f, codec_id)
                        return  # connection consumed by the binary loop
                    f.write(json.dumps({"id": rid, "error": {
                        "kind": "Internal",
                        "msg": f"unsupported codec/framing "
                               f"{codec_id!r}/{framing!r}"}}).encode()
                        + b"\n")
                    f.flush()
                    continue
                if method == "replicate":
                    self._serve_replica(conn, f, rid, params)
                    return  # connection consumed by the stream
                if method == "watch":
                    if not self.primary:
                        f.write(json.dumps(
                            {"id": rid, "error": {
                                "kind": "NotPrimary",
                                "msg": "standby: not serving watches"}})
                            .encode() + b"\n")
                        f.flush()
                        continue
                    self._serve_watch(conn, f, rid, params)
                    return  # connection consumed by the stream
                try:
                    result = self._dispatch(method, params)
                    f.write(json.dumps({"id": rid, "result": result},
                                       default=str).encode() + b"\n")
                except Exception as e:  # noqa: BLE001
                    if not isinstance(e, ApiError):
                        traceback.print_exc()
                    f.write(json.dumps({"id": rid,
                                        "error": error_to_wire(e)})
                            .encode() + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self._drop_conn(conn)

    def _drop_conn(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)
        # shutdown, not just close: the makefile object can outlive this
        # frame (an exception's traceback cycle holds it until a GC
        # pass), and close() alone leaves the fd open while it does —
        # the peer would block on a dead-but-unclosed stream instead of
        # reading EOF (same rule as _serve_replica's teardown)
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _serve_conn_binary(self, conn, f, codec_id: str):
        """Post-negotiation request loop: length-prefixed codec frames in
        both directions (storage/wire.py).  Replication stays on the
        newline-JSON protocol — a standby never negotiates."""
        framer = wire.BinFramer(f, codec_id, site="store.rpc")
        while not self._stop.is_set():
            try:
                req = framer.recv()
            except BrokenPipeError:
                return  # clean close at a frame boundary
            except (wire.FrameTruncated, CodecError, OSError):
                return  # torn/corrupt frame: sever the connection
            rid = req.get("id")
            method = req.get("method")
            params = req.get("params") or {}
            if method == "replicate":
                framer.send({"id": rid, "error": {
                    "kind": "Internal",
                    "msg": "replicate is not served on a binary-framed "
                           "connection; dial a plain one"}})
                continue
            if method == "watch":
                if not self.primary:
                    framer.send({"id": rid, "error": {
                        "kind": "NotPrimary",
                        "msg": "standby: not serving watches"}})
                    continue
                self._serve_watch(conn, f, rid, params, framer=framer)
                return  # connection consumed by the stream
            try:
                result = self._dispatch(method, params)
                framer.send({"id": rid, "result": result})
            except (BrokenPipeError, ConnectionResetError, OSError):
                return
            except Exception as e:  # noqa: BLE001
                if not isinstance(e, ApiError):
                    traceback.print_exc()
                try:
                    framer.send({"id": rid, "error": error_to_wire(e)})
                except OSError:
                    return

    # The store's decoded-object API re-encodes at the edge; here we use the
    # private encoded form directly to avoid a decode+encode per op.
    def _dispatch(self, method: Optional[str], p: dict):
        s = self.store
        if not self.primary and method != "current_revision":
            # current_revision stays answerable for replication-lag
            # monitoring; everything else must go to the primary
            raise NotPrimary("standby store: not serving client operations")
        if method == "create":
            with self._gated_state_errors():
                obj = s.create(p["key"], s._scheme.decode(p["obj"]))
            return self._replicated(s._scheme.encode(obj))
        if method == "get":
            return s._scheme.encode(s.get(p["key"]))
        if method == "list":
            items, rev = s.list(p["prefix"])
            return {"items": [s._scheme.encode(o) for o in items],
                    "rev": rev}
        if method == "list_raw":
            # watch-cache seed path: ship the committed wire form with its
            # keys verbatim — no decode/encode for a whole-store list
            entries, rev = s.list_raw(p["prefix"])
            return {"items": [[k, r, o] for k, r, o in entries], "rev": rev}
        if method == "update_cas":
            with self._gated_state_errors():
                obj = s.update_cas(p["key"], s._scheme.decode(p["obj"]))
            return self._replicated(s._scheme.encode(obj))
        if method == "delete":
            with self._gated_state_errors():
                obj = s.delete(p["key"], p.get("expect_rv", ""))
            return self._replicated(s._scheme.encode(obj))
        if method == "commit_batch":
            # N mutations, one RPC, one store group commit; per-op errors
            # cross as wire error dicts (the batch itself never fails as a
            # unit — it is amortization, not a transaction)
            results = s.commit_batch(p.get("ops") or [])
            wire = []
            max_rev = 0
            for r in results:
                err = r.get("error")
                if err is not None:
                    wire.append({"error": error_to_wire(err)})
                else:
                    max_rev = max(max_rev, int(
                        r["obj"]["metadata"]["resourceVersion"]))
                    wire.append({"obj": r["obj"]})
            gate_rev = max_rev
            if (self.repl_ack_policy == "durable"
                    and any("error" in w for w in wire)):
                # a per-op error answer proves state the way a
                # singleton's does (see _gated_state_errors) — and what
                # it proves may be a revision ANOTHER connection
                # committed after this batch's own max, so the gate must
                # cover the store's current revision, not just the
                # batch's highest successful commit
                gate_rev = max(gate_rev, s.current_revision())
            if gate_rev and (self._replica_acks or self._expect_replicas):
                # one replication-ack gate for the whole batch: every
                # standby must reach the batch's highest revision before
                # any member is acked (same guarantee, 1/N the waits).
                # The unlocked standby-less check mirrors _replicated's
                # fast path — group commits are THE hot write path and
                # must not serialize on _repl_cond when there is no
                # replica to wait for (same benign race, absorbed by the
                # locked re-check inside _await_replication).
                try:
                    if self._await_replication(gate_rev):
                        # the one wait covered N successful ops: the gate
                        # counted its own unprotected ack, the batch's
                        # other members are just as exposed — count them
                        # too or the exported exposure measure undercounts
                        # by N-1 on every transition batch
                        extra = sum(1 for w in wire if "obj" in w) - 1
                        if extra > 0:
                            with self._repl_cond:
                                self.unprotected_acks += extra
                except ReplicationUnavailable as e:
                    # durable: no member of the batch may ack or prove
                    # state — every writer fails 503 and retries (the
                    # WAL-failure precedent: fail the whole batch loudly)
                    unavailable = {"error": error_to_wire(e)}
                    wire = [unavailable for _ in wire]
            elif max_rev and self._ever_attached:
                # degraded window: the batch's successful ops ack
                # unprotected — count each (see _replicated)
                with self._repl_cond:
                    self.unprotected_acks += sum(
                        1 for w in wire if "obj" in w)
            return {"results": wire}
        if method == "get_many":
            return {"items": s.get_raw_many(p.get("keys") or [])}
        if method == "current_revision":
            return s.current_revision()
        if method == "compact":
            s.compact(p.get("keep_last", 1000))
            return None
        raise ValueError(f"unknown store method {method!r}")

    def promote(self):
        """Standby -> primary: start serving client operations."""
        self.primary = True

    # ------------------------------------------------------------ replication

    @contextlib.contextmanager
    def _gated_state_errors(self):
        """Durable policy: a conflict-class answer (AlreadyExists /
        Conflict / NotFound...) PROVES server state to the client — a
        writer whose first attempt's ack failed at the gate retries,
        reads AlreadyExists off the doomed primary, and would launder an
        unreplicated commit into a durable-looking ack.  So such answers
        ship only once every attached standby has caught up to the
        revision window they prove; a gate timeout answers 503 instead
        and the client keeps retrying until the standby has the state
        too.  Identity under the available policy."""
        if self.repl_ack_policy != "durable":
            yield
            return
        try:
            yield
        except ApiError:
            self._await_replication(self.store.current_revision())
            raise

    def _replicated(self, encoded: dict) -> dict:
        """Gate one write's ack on replication (see _await_replication)."""
        # unlocked fast path for the standby-less deployment: same benign
        # race the locked re-check in _await_replication absorbs, and it
        # keeps singleton writes off the shared _repl_cond
        if not self._replica_acks and not self._expect_replicas:
            if self._ever_attached:
                # degraded window (the standby died and the timeout reset
                # the expectation): EVERY ack until one reattaches goes
                # out unprotected, not just the writes in flight at the
                # timeout — count them all or the exported exposure
                # measure lies to the operator
                with self._repl_cond:
                    self.unprotected_acks += 1
            return encoded
        self._await_replication(int(encoded["metadata"]["resourceVersion"]))
        return encoded

    def _await_replication(self, rev: int):
        """Semi-synchronous replication gate: a write is acked to the
        client only after every attached standby has acked its revision —
        so a SIGKILLed primary cannot take an acknowledged write with it.
        If a standby is EXPECTED (one attached before) but currently
        DISCONNECTED — a link flap mid-resync — the ack WAITS for it to
        reattach and catch up, under the same timeout; returning
        immediately there acked writes unprotected exactly when the link
        was least trustworthy.  What a timeout means is the
        repl_ack_policy (see __init__): available counts + logs an
        unprotected ack (laggards dropped, absent standbys stop being
        expected); durable raises ReplicationUnavailable — no ack, the
        client retries — the etcd answer is quorum; with exactly two
        members, this knob is the documented tradeoff.

        Returns True when the ack goes out UNPROTECTED (counted once
        here): batch callers gating N ops on one wait use it to count
        the other N-1 exposed acks."""
        deadline = time.monotonic() + REPLICATION_ACK_TIMEOUT_SECONDS
        with self._repl_cond:
            if not self._replica_acks and not self._expect_replicas:
                if self._ever_attached:
                    self.unprotected_acks += 1  # degraded window: exposed
                    return True
                return False
            while True:
                if not self._replica_acks and not self._expect_replicas:
                    # another writer's timeout already reset the
                    # expectation (absent/dropped standby): this write
                    # rides the same unprotected verdict instead of
                    # burning its own remaining timeout parked on a
                    # condition that can no longer come true.  It still
                    # COUNTS — it was in flight during the window and
                    # goes out unprotected just like the writer that
                    # timed out (the exported counter is the operator's
                    # measure of the exposure, not of timeout events).
                    self.unprotected_acks += 1
                    return True
                laggards = [fd for fd, acked in self._replica_acks.items()
                            if acked < rev]
                if self._replica_acks and not laggards:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._repl_cond.wait(remaining)
            if not self._replica_acks:
                if self.repl_ack_policy == "durable":
                    # expectation stays armed: every answer keeps failing
                    # 503 until a standby reattaches and catches up —
                    # write availability is what durable trades away
                    raise ReplicationUnavailable(
                        f"rev {rev} unreplicated: standby absent for "
                        f"{REPLICATION_ACK_TIMEOUT_SECONDS}s")
                # expected standby never came back inside the window:
                # stop expecting (writes go back to fast, unprotected
                # acks) until one reattaches
                self._expect_replicas = False
                self.unprotected_acks += 1
                # wake writers parked in the wait loop above: their
                # condition can no longer come true and they'd otherwise
                # each burn their own remaining timeout
                self._repl_cond.notify_all()
                print(f"store: acking rev {rev} UNPROTECTED — standby "
                      f"absent for {REPLICATION_ACK_TIMEOUT_SECONDS}s; "
                      f"expectation reset until one reattaches",
                      flush=True)
                return True
            if self.repl_ack_policy == "durable":
                # drop the laggards (reconnect + resync from the acked
                # cursor is their fastest path back to current) but keep
                # the expectation armed and fail this answer — durable
                # never converts a timeout into an ack
                for fd in laggards:
                    self._drop_laggard_locked(fd, rev)
                raise ReplicationUnavailable(
                    f"rev {rev} unreplicated: standby "
                    f"{REPLICATION_ACK_TIMEOUT_SECONDS}s behind; dropped "
                    f"for resync")
            # deliberate drop = deliberate unprotection: the laggard cost
            # this write the full timeout and availability won — if it was
            # the LAST standby, writes go back to fast, unprotected acks
            # until one REATTACHES (re-arming the expectation); leaving
            # the expectation armed there made every subsequent write pay
            # the timeout too, a 2s/write wedge the laggard contract
            # explicitly forbids.  With another healthy standby still
            # acking, the expectation stays armed: a later flap of ITS
            # link must keep waiting (disarming globally here silently
            # reopened the unprotected reconnect window for it).
            for fd in laggards:
                self._drop_laggard_locked(fd, rev)
            self._expect_replicas = bool(self._replica_acks)
            self._repl_cond.notify_all()  # release parked writers (see above)
            if not self._replica_acks:
                # every replica that could have covered this rev was just
                # dropped: this ack is as unprotected as the absent case
                self.unprotected_acks += 1
                print(f"store: acking rev {rev} UNPROTECTED — laggard "
                      f"standby dropped; expectation reset until one "
                      f"reattaches", flush=True)
                return True
            return False

    def _drop_laggard_locked(self, fd, rev: int):
        """Detach one laggard replication feed (caller holds _repl_cond)."""
        print(f"store: dropping laggard standby (rev {rev} unacked "
              f"after {REPLICATION_ACK_TIMEOUT_SECONDS}s)",
              flush=True)
        self._replica_acks.pop(fd, None)
        fd._stopped.set()
        fd._q.put(None)
        # sever the socket too: a wedged standby (SIGSTOP, full
        # buffer) leaves send_loop blocked in flush() where the
        # queue sentinel can't wake it — only shutdown() can
        drop = getattr(fd, "drop_conn", None)
        if drop is not None:
            drop()

    def _serve_replica(self, conn, f, rid, params):
        """A standby's connection: stream commit records to it, read its
        {"ack": rev} lines back on the same socket (reads here, writes on
        the sender thread — the two directions have independent buffers)."""
        since_rev = int(params.get("since_rev", 0))
        feed = self.store.replication_feed(since_rev)

        def drop_conn():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

        feed.drop_conn = drop_conn
        with self._repl_cond:
            # the standby resumes from its last ACKED rev, so it durably
            # holds everything <= since_rev; seeding 0 made a caught-up
            # reconnector look like a laggard to writers parked on old revs
            # (2s stall + spurious drop when its final ack died in a sever)
            self._replica_acks[feed] = since_rev
            self._expect_replicas = True
            self._ever_attached = True
            self._repl_cond.notify_all()  # wake writes parked on the flap
        f.write(json.dumps({"id": rid, "result": {
            "rev": self.store.current_revision()}}).encode() + b"\n")
        f.flush()

        def send(data: bytes):
            """One replication write, subject to fault injection: an
            injected sever writes a strict PREFIX (the torn frame the
            standby's parser chokes on) then raises — the except below
            tears the session down and the standby reconnect-resyncs
            from its last acked revision."""
            exc = None
            if faultline.active():
                data, exc = faultline.filter_bytes("repl.link", data)
            if data:
                f.write(data)
            if exc is not None:
                f.flush()
                raise exc

        def send_loop():
            try:
                if feed.snapshot is not None:
                    items, rev = feed.snapshot
                    send(json.dumps({"snap": {
                        "items": [[k, r, o] for k, r, o in items],
                        "rev": rev}}).encode() + b"\n")
                    f.flush()
                while not self._stop.is_set() and not feed._stopped.is_set():
                    recs = feed.next_batch_timeout(WATCH_HEARTBEAT_SECONDS)
                    if recs is None:
                        if feed._stopped.is_set():
                            break
                        send(b"\n")  # heartbeat
                    else:
                        # per-record frames (the standby applies and acks
                        # each), ONE write+flush per group commit
                        send(b"".join(
                            json.dumps({"rec": {
                                "rev": rev, "type": typ, "key": key,
                                "obj": obj}}).encode() + b"\n"
                            for rev, typ, key, obj in recs))
                    f.flush()
            except (BrokenPipeError, ConnectionResetError, OSError,
                    ValueError):
                pass
            finally:
                # shutdown, not just close: the ack reader below still
                # holds the makefile object, so close() alone would keep
                # the fd open and neither side would ever see EOF
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass

        sender = threading.Thread(target=send_loop, daemon=True,
                                  name="store-replica-send")
        sender.start()
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    acked = int(json.loads(line).get("ack", 0))
                except (ValueError, TypeError):
                    continue
                with self._repl_cond:
                    if feed in self._replica_acks:
                        self._replica_acks[feed] = max(
                            self._replica_acks[feed], acked)
                    self._repl_cond.notify_all()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            pass
        finally:
            feed.stop(self.store)
            with self._repl_cond:
                self._replica_acks.pop(feed, None)
                self._repl_cond.notify_all()
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _stamp_commit_ts(self, env: dict, evs) -> None:
        """Watch-lag SLI: attach the monotonic commit stamp of the
        frame's NEWEST revision ("ts" + "ts_rev") so the apiserver's
        cacher — fed by this stream — can answer commit_ts_of for the
        revisions it serves.  One stamp per frame: the frame is the
        delivery unit whose lag is measurable.  Old clients ignore the
        extra keys; a stamp aged out of the ring is simply omitted."""
        fn = getattr(self.store, "commit_ts_of", None)
        if fn is None:
            return
        max_rev = 0
        for ev in evs:
            try:
                rev = int((ev.object.get("metadata") or {})
                          .get("resourceVersion") or 0)
            except (TypeError, ValueError, AttributeError):
                continue
            if rev > max_rev:
                max_rev = rev
        if max_rev:
            ts = fn(max_rev)
            if ts is not None:
                env["ts"] = round(ts, 6)
                env["ts_rev"] = max_rev

    def _serve_watch(self, conn, f, rid, params, framer=None):
        """framer=None is the legacy newline-JSON stream; a BinFramer
        switches frames to length-prefixed codec payloads whose event
        objects are per-revision cached bytes (Scheme.encode_bytes with
        the codec id in the cache key) spliced into the envelope — one
        encode serves every binary watcher of a revision, and one
        send_payloads call ships a whole group-commit batch."""
        try:
            kw = {}
            if "queue_limit" in params:
                kw["queue_limit"] = int(params["queue_limit"])
            w = self.store.watch(params.get("prefix", ""),
                                 int(params.get("since_rev", 0)), **kw)
        except Exception as e:  # noqa: BLE001
            err = {"id": rid, "error": error_to_wire(e)}
            if framer is not None:
                framer.send(err)
            else:
                f.write(json.dumps(err).encode() + b"\n")
                f.flush()
            return
        if framer is not None:
            framer.site = "store.watch"  # stream faults tear watch frames
            framer.send({"id": rid, "result": "ok"})
            scheme = self.store._scheme
        else:
            f.write(json.dumps({"id": rid, "result": "ok"}).encode() + b"\n")
            f.flush()
        try:
            while not self._stop.is_set():
                # progress floor read BEFORE the wait: any commit <= this
                # revision fanned out to w (under the store lock) before
                # current_revision returned, so a timed-out wait proves the
                # client has already received everything up to it — safe
                # to stamp on the heartbeat (etcd progress-notify)
                rev_floor = self.store.current_revision()
                evs = w.next_batch_timeout(WATCH_HEARTBEAT_SECONDS)
                if evs is None:
                    if w.evicted or w._stopped.is_set():
                        # slow remote consumer: end the stream — the
                        # client-side watcher reads EOF as a dead stream
                        # and its cacher reseeds with a fresh list
                        break
                    if framer is not None:
                        framer.send({"progress": {"rev": rev_floor}})
                    else:
                        f.write(json.dumps(
                            {"progress": {"rev": rev_floor}})
                            .encode() + b"\n")
                elif framer is not None:
                    if framer.codec_id == "json":
                        # length-prefixed JSON: no bytes values allowed in
                        # the envelope, ship plain object dicts
                        env = {"events": [
                            {"type": ev.type, "object": ev.object}
                            for ev in evs]}
                        self._stamp_commit_ts(env, evs)
                        framer.send(env)
                    else:
                        env = {"events": [
                            {"type": ev.type,
                             "objraw": scheme.encode_bytes(
                                 ev.object, codec=framer.codec_id)}
                            for ev in evs]}
                        self._stamp_commit_ts(env, evs)
                        framer.send(env)
                else:
                    # one frame, one flush, one client-side wakeup per
                    # group commit (singletons ride the legacy "event" key)
                    if len(evs) == 1:
                        env = {"event": {"type": evs[0].type,
                                         "object": evs[0].object}}
                    else:
                        env = {"events": [
                            {"type": ev.type, "object": ev.object}
                            for ev in evs]}
                    self._stamp_commit_ts(env, evs)
                    f.write(json.dumps(env).encode() + b"\n")
                if framer is None:
                    f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            pass
        finally:
            w.stop()
            # shutdown first: a torn frame's exception traceback can pin
            # the makefile past this frame, and the client must see EOF
            # NOW, not at the next GC pass (see _drop_conn)
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
