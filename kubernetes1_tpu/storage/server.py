"""StoreServer: the L0 store behind its own socket — the etcd role.

Ref: the reference's L0 is a separately-clustered etcd behind N stateless
apiservers (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:152,263
— every apiserver is just an etcd client).  Splitting the MVCC store into
its own process gives this framework the same shape: the store process is
the single source of truth and any number of apiservers (each running the
full authn/admission/REST stack) serve one cluster, with leader-elected
controllers/schedulers behind them.  Control-plane HA then means "kill any
apiserver; clients fail over; nothing is lost" — the store's WAL covers
store-process restarts.

Wire protocol (newline-JSON over AF_UNIX or TCP, optionally TLS):
  request:  {"id": N, "method": "...", "params": {...}}\n
  response: {"id": N, "result": ...} | {"id": N, "error": {"kind","msg"}}\n
A `watch` request commits its CONNECTION to streaming: after the ack, the
server pushes {"event": {"type", "object"}} frames (blank lines are
heartbeats) until either side closes.  Objects cross as their encoded dict
form — the scheme lives in the clients.

Why not raft here: etcd's quorum is WHY the reference gets store HA for
free, but a correct raft is a project of its own.  This server + WAL gives
apiserver-level HA now (the VERDICT r3 bar: survive apiserver death) and
keeps L0 behind one interface a raft group could replace later.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import traceback
from typing import Optional, Tuple, Union

from ..machinery import (
    AlreadyExists,
    ApiError,
    Conflict,
    NotFound,
    TooOldResourceVersion,
)
from .store import Store

_ERROR_KINDS = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "TooOldResourceVersion": TooOldResourceVersion,
}

WATCH_HEARTBEAT_SECONDS = 5.0


def error_to_wire(e: Exception) -> dict:
    for kind, cls in _ERROR_KINDS.items():
        if isinstance(e, cls):
            return {"kind": kind, "msg": str(e)}
    return {"kind": "Internal", "msg": f"{type(e).__name__}: {e}"}


def error_from_wire(err: dict) -> Exception:
    cls = _ERROR_KINDS.get(err.get("kind", ""), ApiError)
    return cls(err.get("msg", "store error"))


class StoreServer:
    """Serves a Store over a unix or TCP socket.  The store's scheme is
    only used for encode/decode at the edges; the server deals in the
    encoded dict representation throughout (no double decode)."""

    def __init__(self, store: Store, address: Union[str, Tuple[str, int]],
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = ""):
        """The store IS the cluster — its socket must never be an
        unauthenticated bypass of the apiserver's authz stack.  Unix
        sockets are chmod 0600 (same-user only, the etcd-on-localhost
        posture); TCP mode with client_ca_file REQUIRES a client cert
        signed by that CA (etcd's peer/client mTLS)."""
        self.store = store
        self._threads = []
        self._stop = threading.Event()
        if isinstance(address, str):
            try:
                os.unlink(address)
            except FileNotFoundError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(address)
            os.chmod(address, 0o600)
            self.address: Union[str, Tuple[str, int]] = address
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind(address)
            self.address = self._sock.getsockname()[:2]
        if tls_cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert_file,
                                keyfile=tls_key_file or None)
            if client_ca_file:
                ctx.load_verify_locations(cafile=client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._sock = ctx.wrap_socket(self._sock, server_side=True,
                                         do_handshake_on_connect=False)
        self._sock.listen(64)

    def start(self) -> "StoreServer":
        from ..utils.gctune import tune_for_server

        tune_for_server()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="store-server")
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self.store.close()

    # ----------------------------------------------------------------- serve

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn):
        handshake = getattr(conn, "do_handshake", None)
        try:
            if handshake is not None:
                handshake()
        except (OSError, ValueError):
            conn.close()
            return
        f = conn.makefile("rwb")
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except ValueError:
                    break
                rid = req.get("id")
                method = req.get("method")
                params = req.get("params") or {}
                if method == "watch":
                    self._serve_watch(conn, f, rid, params)
                    return  # connection consumed by the stream
                try:
                    result = self._dispatch(method, params)
                    f.write(json.dumps({"id": rid, "result": result},
                                       default=str).encode() + b"\n")
                except Exception as e:  # noqa: BLE001
                    if not isinstance(e, ApiError):
                        traceback.print_exc()
                    f.write(json.dumps({"id": rid,
                                        "error": error_to_wire(e)})
                            .encode() + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # The store's decoded-object API re-encodes at the edge; here we use the
    # private encoded form directly to avoid a decode+encode per op.
    def _dispatch(self, method: Optional[str], p: dict):
        s = self.store
        if method == "create":
            obj = s.create(p["key"], s._scheme.decode(p["obj"]))
            return s._scheme.encode(obj)
        if method == "get":
            return s._scheme.encode(s.get(p["key"]))
        if method == "list":
            items, rev = s.list(p["prefix"])
            return {"items": [s._scheme.encode(o) for o in items],
                    "rev": rev}
        if method == "update_cas":
            obj = s.update_cas(p["key"], s._scheme.decode(p["obj"]))
            return s._scheme.encode(obj)
        if method == "delete":
            obj = s.delete(p["key"], p.get("expect_rv", ""))
            return s._scheme.encode(obj)
        if method == "current_revision":
            return s.current_revision()
        if method == "compact":
            s.compact(p.get("keep_last", 1000))
            return None
        raise ValueError(f"unknown store method {method!r}")

    def _serve_watch(self, conn, f, rid, params):
        try:
            w = self.store.watch(params.get("prefix", ""),
                                 int(params.get("since_rev", 0)))
        except Exception as e:  # noqa: BLE001
            f.write(json.dumps({"id": rid, "error": error_to_wire(e)})
                    .encode() + b"\n")
            f.flush()
            return
        f.write(json.dumps({"id": rid, "result": "ok"}).encode() + b"\n")
        f.flush()
        try:
            while not self._stop.is_set():
                ev = w.next_timeout(WATCH_HEARTBEAT_SECONDS)
                if ev is None:
                    f.write(b"\n")  # heartbeat: detect half-open peers
                else:
                    # store watch events already carry the encoded dict form
                    f.write(json.dumps(
                        {"event": {"type": ev.type, "object": ev.object}})
                        .encode() + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            pass
        finally:
            w.stop()
            try:
                conn.close()
            except OSError:
                pass
