"""StoreServer: the L0 store behind its own socket — the etcd role.

Ref: the reference's L0 is a separately-clustered etcd behind N stateless
apiservers (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:152,263
— every apiserver is just an etcd client).  Splitting the MVCC store into
its own process gives this framework the same shape: the store process is
the single source of truth and any number of apiservers (each running the
full authn/admission/REST stack) serve one cluster, with leader-elected
controllers/schedulers behind them.  Control-plane HA then means "kill any
apiserver; clients fail over; nothing is lost" — the store's WAL covers
store-process restarts.

Wire protocol (newline-JSON over AF_UNIX or TCP, optionally TLS):
  request:  {"id": N, "method": "...", "params": {...}}\n
  response: {"id": N, "result": ...} | {"id": N, "error": {"kind","msg"}}\n
A `watch` request commits its CONNECTION to streaming: after the ack, the
server pushes {"event": {"type", "object"}} frames — or, when a group
commit delivered several at once, ONE {"events": [{"type", "object"},
...]} frame (one socket write+flush and one client-side queue wakeup per
batch) — until either side closes.  Heartbeats are {"progress": {"rev":
N}} frames stamping the store revision (the etcd progress-notify /
watch-bookmark analog: the client's cacher tracks freshness from the
stream instead of polling current_revision); blank lines remain accepted
as legacy heartbeats.  Objects cross as their encoded dict form — the
scheme lives in the clients.

The `commit_batch` method ships N mutations in one RPC and one store
group commit ({"ops": [{"op", "key", "obj"?, "expect_rv"?}, ...]} ->
{"results": [{"obj": ...} | {"error": ...}, ...]}); `get_many` is its
read half.

Why not raft here: etcd's quorum is WHY the reference gets store HA for
free, but a correct raft is a project of its own.  This server + WAL gives
apiserver-level HA now (the VERDICT r3 bar: survive apiserver death) and
keeps L0 behind one interface a raft group could replace later.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import traceback
from typing import Optional, Tuple, Union

from ..machinery import (
    AlreadyExists,
    ApiError,
    Conflict,
    NotFound,
    TooOldResourceVersion,
)
from .store import Store
from ..utils import locksan

class NotPrimary(ApiError):
    """Raised by a standby store for any client operation before promotion.
    The client (RemoteStore) treats it as 'try the next server' — the
    request was definitely NOT applied, so failover-retry is always safe."""


_ERROR_KINDS = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "TooOldResourceVersion": TooOldResourceVersion,
    "NotPrimary": NotPrimary,
}

WATCH_HEARTBEAT_SECONDS = 5.0
# How long a write waits for the standby's ack before the standby is
# declared a laggard and dropped (availability over a stuck replica —
# the dropped standby reconnects and resyncs; the un-replicated window
# is logged).  See StoreServer._await_replication.
REPLICATION_ACK_TIMEOUT_SECONDS = 2.0


def error_to_wire(e: Exception) -> dict:
    for kind, cls in _ERROR_KINDS.items():
        if isinstance(e, cls):
            return {"kind": kind, "msg": str(e)}
    return {"kind": "Internal", "msg": f"{type(e).__name__}: {e}"}


def error_from_wire(err: dict) -> Exception:
    cls = _ERROR_KINDS.get(err.get("kind", ""), ApiError)
    return cls(err.get("msg", "store error"))


class StoreServer:
    """Serves a Store over a unix or TCP socket.  The store's scheme is
    only used for encode/decode at the edges; the server deals in the
    encoded dict representation throughout (no double decode)."""

    def __init__(self, store: Store, address: Union[str, Tuple[str, int]],
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = "", primary: bool = True):
        """The store IS the cluster — its socket must never be an
        unauthenticated bypass of the apiserver's authz stack.  Unix
        sockets are chmod 0600 (same-user only, the etcd-on-localhost
        posture); TCP mode with client_ca_file REQUIRES a client cert
        signed by that CA (etcd's peer/client mTLS).

        primary=False serves a warm standby: every client operation
        answers NotPrimary (so RemoteStore fails over to the real primary)
        until promote() flips it live."""
        self.store = store
        self.primary = primary
        self._threads = []
        self._stop = threading.Event()
        # replication: feed -> last acked rev, guarded by _repl_cond
        self._repl_cond = locksan.make_condition(name="StoreServer._repl_cond")
        self._replica_acks: dict = {}
        if isinstance(address, str):
            try:
                os.unlink(address)
            except FileNotFoundError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(address)
            os.chmod(address, 0o600)
            self.address: Union[str, Tuple[str, int]] = address
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind(address)
            self.address = self._sock.getsockname()[:2]
        if tls_cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert_file,
                                keyfile=tls_key_file or None)
            if client_ca_file:
                ctx.load_verify_locations(cafile=client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._sock = ctx.wrap_socket(self._sock, server_side=True,
                                         do_handshake_on_connect=False)
        self._sock.listen(64)

    def start(self) -> "StoreServer":
        from ..utils.gctune import tune_for_server

        tune_for_server()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="store-server")
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self.store.close()

    # ----------------------------------------------------------------- serve

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn):
        handshake = getattr(conn, "do_handshake", None)
        try:
            if handshake is not None:
                handshake()
        except (OSError, ValueError):
            conn.close()
            return
        f = conn.makefile("rwb")
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except ValueError:
                    break
                rid = req.get("id")
                method = req.get("method")
                params = req.get("params") or {}
                if method == "replicate":
                    self._serve_replica(conn, f, rid, params)
                    return  # connection consumed by the stream
                if method == "watch":
                    if not self.primary:
                        f.write(json.dumps(
                            {"id": rid, "error": {
                                "kind": "NotPrimary",
                                "msg": "standby: not serving watches"}})
                            .encode() + b"\n")
                        f.flush()
                        continue
                    self._serve_watch(conn, f, rid, params)
                    return  # connection consumed by the stream
                try:
                    result = self._dispatch(method, params)
                    f.write(json.dumps({"id": rid, "result": result},
                                       default=str).encode() + b"\n")
                except Exception as e:  # noqa: BLE001
                    if not isinstance(e, ApiError):
                        traceback.print_exc()
                    f.write(json.dumps({"id": rid,
                                        "error": error_to_wire(e)})
                            .encode() + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # The store's decoded-object API re-encodes at the edge; here we use the
    # private encoded form directly to avoid a decode+encode per op.
    def _dispatch(self, method: Optional[str], p: dict):
        s = self.store
        if not self.primary and method != "current_revision":
            # current_revision stays answerable for replication-lag
            # monitoring; everything else must go to the primary
            raise NotPrimary("standby store: not serving client operations")
        if method == "create":
            obj = s.create(p["key"], s._scheme.decode(p["obj"]))
            return self._replicated(s._scheme.encode(obj))
        if method == "get":
            return s._scheme.encode(s.get(p["key"]))
        if method == "list":
            items, rev = s.list(p["prefix"])
            return {"items": [s._scheme.encode(o) for o in items],
                    "rev": rev}
        if method == "list_raw":
            # watch-cache seed path: ship the committed wire form with its
            # keys verbatim — no decode/encode for a whole-store list
            entries, rev = s.list_raw(p["prefix"])
            return {"items": [[k, r, o] for k, r, o in entries], "rev": rev}
        if method == "update_cas":
            obj = s.update_cas(p["key"], s._scheme.decode(p["obj"]))
            return self._replicated(s._scheme.encode(obj))
        if method == "delete":
            obj = s.delete(p["key"], p.get("expect_rv", ""))
            return self._replicated(s._scheme.encode(obj))
        if method == "commit_batch":
            # N mutations, one RPC, one store group commit; per-op errors
            # cross as wire error dicts (the batch itself never fails as a
            # unit — it is amortization, not a transaction)
            results = s.commit_batch(p.get("ops") or [])
            wire = []
            max_rev = 0
            for r in results:
                err = r.get("error")
                if err is not None:
                    wire.append({"error": error_to_wire(err)})
                else:
                    max_rev = max(max_rev, int(
                        r["obj"]["metadata"]["resourceVersion"]))
                    wire.append({"obj": r["obj"]})
            if max_rev:
                # one replication-ack gate for the whole batch: every
                # standby must reach the batch's highest revision before
                # any member is acked (same guarantee, 1/N the waits)
                self._await_replication(max_rev)
            return {"results": wire}
        if method == "get_many":
            return {"items": s.get_raw_many(p.get("keys") or [])}
        if method == "current_revision":
            return s.current_revision()
        if method == "compact":
            s.compact(p.get("keep_last", 1000))
            return None
        raise ValueError(f"unknown store method {method!r}")

    def promote(self):
        """Standby -> primary: start serving client operations."""
        self.primary = True

    # ------------------------------------------------------------ replication

    def _replicated(self, encoded: dict) -> dict:
        """Gate one write's ack on replication (see _await_replication)."""
        if self._replica_acks:
            self._await_replication(
                int(encoded["metadata"]["resourceVersion"]))
        return encoded

    def _await_replication(self, rev: int):
        """Semi-synchronous replication gate: a write is acked to the
        client only after every attached standby has acked its revision —
        so a SIGKILLed primary cannot take an acknowledged write with it.
        A standby that stalls past the timeout is DROPPED (it reconnects
        and resyncs) rather than wedging the control plane: the etcd
        answer is quorum; with exactly two members, availability wins."""
        if not self._replica_acks:
            return
        deadline = time.monotonic() + REPLICATION_ACK_TIMEOUT_SECONDS
        with self._repl_cond:
            while True:
                laggards = [fd for fd, acked in self._replica_acks.items()
                            if acked < rev]
                if not laggards:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._repl_cond.wait(remaining)
            for fd in laggards:
                print(f"store: dropping laggard standby (rev {rev} unacked "
                      f"after {REPLICATION_ACK_TIMEOUT_SECONDS}s)",
                      flush=True)
                self._replica_acks.pop(fd, None)
                fd._stopped.set()
                fd._q.put(None)
                # sever the socket too: a wedged standby (SIGSTOP, full
                # buffer) leaves send_loop blocked in flush() where the
                # queue sentinel can't wake it — only shutdown() can
                drop = getattr(fd, "drop_conn", None)
                if drop is not None:
                    drop()

    def _serve_replica(self, conn, f, rid, params):
        """A standby's connection: stream commit records to it, read its
        {"ack": rev} lines back on the same socket (reads here, writes on
        the sender thread — the two directions have independent buffers)."""
        feed = self.store.replication_feed(int(params.get("since_rev", 0)))

        def drop_conn():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

        feed.drop_conn = drop_conn
        with self._repl_cond:
            self._replica_acks[feed] = 0
        f.write(json.dumps({"id": rid, "result": {
            "rev": self.store.current_revision()}}).encode() + b"\n")
        f.flush()

        def send_loop():
            try:
                if feed.snapshot is not None:
                    items, rev = feed.snapshot
                    f.write(json.dumps({"snap": {
                        "items": [[k, r, o] for k, r, o in items],
                        "rev": rev}}).encode() + b"\n")
                    f.flush()
                while not self._stop.is_set() and not feed._stopped.is_set():
                    recs = feed.next_batch_timeout(WATCH_HEARTBEAT_SECONDS)
                    if recs is None:
                        if feed._stopped.is_set():
                            break
                        f.write(b"\n")  # heartbeat
                    else:
                        # per-record frames (the standby applies and acks
                        # each), ONE write+flush per group commit
                        f.write(b"".join(
                            json.dumps({"rec": {
                                "rev": rev, "type": typ, "key": key,
                                "obj": obj}}).encode() + b"\n"
                            for rev, typ, key, obj in recs))
                    f.flush()
            except (BrokenPipeError, ConnectionResetError, OSError,
                    ValueError):
                pass
            finally:
                # shutdown, not just close: the ack reader below still
                # holds the makefile object, so close() alone would keep
                # the fd open and neither side would ever see EOF
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass

        sender = threading.Thread(target=send_loop, daemon=True,
                                  name="store-replica-send")
        sender.start()
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    acked = int(json.loads(line).get("ack", 0))
                except (ValueError, TypeError):
                    continue
                with self._repl_cond:
                    if feed in self._replica_acks:
                        self._replica_acks[feed] = max(
                            self._replica_acks[feed], acked)
                    self._repl_cond.notify_all()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            pass
        finally:
            feed.stop(self.store)
            with self._repl_cond:
                self._replica_acks.pop(feed, None)
                self._repl_cond.notify_all()
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _serve_watch(self, conn, f, rid, params):
        try:
            kw = {}
            if "queue_limit" in params:
                kw["queue_limit"] = int(params["queue_limit"])
            w = self.store.watch(params.get("prefix", ""),
                                 int(params.get("since_rev", 0)), **kw)
        except Exception as e:  # noqa: BLE001
            f.write(json.dumps({"id": rid, "error": error_to_wire(e)})
                    .encode() + b"\n")
            f.flush()
            return
        f.write(json.dumps({"id": rid, "result": "ok"}).encode() + b"\n")
        f.flush()
        try:
            while not self._stop.is_set():
                # progress floor read BEFORE the wait: any commit <= this
                # revision fanned out to w (under the store lock) before
                # current_revision returned, so a timed-out wait proves the
                # client has already received everything up to it — safe
                # to stamp on the heartbeat (etcd progress-notify)
                rev_floor = self.store.current_revision()
                evs = w.next_batch_timeout(WATCH_HEARTBEAT_SECONDS)
                if evs is None:
                    if w.evicted or w._stopped.is_set():
                        # slow remote consumer: end the stream — the
                        # client-side watcher reads EOF as a dead stream
                        # and its cacher reseeds with a fresh list
                        break
                    f.write(json.dumps(
                        {"progress": {"rev": rev_floor}}).encode() + b"\n")
                elif len(evs) == 1:
                    # store watch events already carry the encoded dict form
                    f.write(json.dumps(
                        {"event": {"type": evs[0].type,
                                   "object": evs[0].object}})
                        .encode() + b"\n")
                else:
                    # one frame, one flush, one client-side wakeup per
                    # group commit
                    f.write(json.dumps(
                        {"events": [{"type": ev.type, "object": ev.object}
                                    for ev in evs]}).encode() + b"\n")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            pass
        finally:
            w.stop()
            try:
                conn.close()
            except OSError:
                pass
