"""Warm-standby store: WAL shipping + self-promotion on primary death.

Ref role: the reference's L0 survives member loss because etcd is a raft
quorum and apiservers are just clients (staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go:152,263).  This is the two-member analog: the
standby replays the primary's commit stream into an identical local store
(same revision numbering, own WAL), acks each applied revision — the
primary gates client write-acks on those acks, so an acknowledged write
exists on BOTH disks — and serves NotPrimary to clients until promoted.

Promotion is self-driven: when the replication link drops, the standby
probes the primary's address for `failover_grace` seconds; only a
connection REFUSED verdict (process dead — on a unix socket this is
immediate and unambiguous) promotes.  A transient hiccup with the primary
still listening just reconnects and resyncs.  Split-brain caveat vs raft:
over TCP across hosts a network partition is indistinguishable from death;
a real quorum needs >= 3 members — documented tradeoff, the interface is
shaped so a raft group can replace this later (storage/server.py:21).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Optional, Tuple, Union

from ..machinery.scheme import Scheme, global_scheme
from .server import StoreServer
from .store import Store


class StandbyServer:
    """Runs a Store fed only by replication + a StoreServer in standby
    mode; promotes itself when the primary is observed dead."""

    def __init__(self, primary_address: Union[str, Tuple[str, int]],
                 serve_address: Union[str, Tuple[str, int]],
                 wal_path: Optional[str] = None,
                 failover_grace: float = 1.0,
                 scheme: Optional[Scheme] = None,
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = "",
                 primary_ca_file: str = "", primary_cert_file: str = "",
                 primary_key_file: str = ""):
        self.primary_address = primary_address
        self.failover_grace = failover_grace
        # a TLS-enabled primary (TCP+mTLS deployment) needs a TLS dial for
        # the replication stream — a plaintext handshake would just die
        self._ssl_ctx = None
        if primary_ca_file:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl_ctx.load_verify_locations(cafile=primary_ca_file)
            if primary_cert_file:
                self._ssl_ctx.load_cert_chain(
                    certfile=primary_cert_file,
                    keyfile=primary_key_file or None)
        self.store = Store(scheme or global_scheme.copy(), wal_path=wal_path)
        self.server = StoreServer(self.store, serve_address,
                                  tls_cert_file=tls_cert_file,
                                  tls_key_file=tls_key_file,
                                  client_ca_file=client_ca_file,
                                  primary=False)
        self.address = self.server.address
        self.promoted = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_applied_rev = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "StandbyServer":
        self.server.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="store-standby")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.server.stop()

    def promote(self):
        """Standby -> primary.  Its store already holds every acknowledged
        write (the primary's ack gate guarantees it)."""
        if not self.promoted.is_set():
            self.promoted.set()
            self.server.promote()
            print(f"ktpu-store standby PROMOTED at rev "
                  f"{self.store.current_revision()}", flush=True)

    # ----------------------------------------------------------- replication

    def _dial(self, timeout: float = 5.0, tls: bool = True):
        if isinstance(self.primary_address, str):
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(timeout)
            conn.connect(self.primary_address)
        else:
            conn = socket.create_connection(tuple(self.primary_address),
                                            timeout=timeout)
        if tls and self._ssl_ctx is not None:
            host = self.primary_address if \
                isinstance(self.primary_address, str) \
                else self.primary_address[0]
            conn = self._ssl_ctx.wrap_socket(conn, server_hostname=host)
        return conn

    def _run(self):
        while not self._stop.is_set() and not self.promoted.is_set():
            try:
                self._stream_once()
            except (OSError, ValueError):
                pass
            if self._stop.is_set() or self.promoted.is_set():
                return
            if self._primary_dead():
                self.promote()
                return
            time.sleep(0.1)  # primary alive: transient drop — resync

    def _stream_once(self):
        """One replication session: handshake, then apply records until the
        connection drops."""
        conn = self._dial()
        try:
            f = conn.makefile("rwb")
            f.write(json.dumps({
                "id": 1, "method": "replicate",
                "params": {"since_rev": self.store.current_revision()}})
                .encode() + b"\n")
            f.flush()
            line = f.readline()
            if not line:
                return
            resp = json.loads(line)
            if resp.get("error"):
                # primary refused (e.g. itself a standby): wait and retry
                time.sleep(0.2)
                return
            conn.settimeout(None)  # stream blocks until commits arrive
            for line in f:
                line = line.strip()
                if not line:
                    continue  # heartbeat
                frame = json.loads(line)
                snap = frame.get("snap")
                if snap is not None:
                    self.store.apply_snapshot(
                        [(k, r, o) for k, r, o in snap["items"]],
                        int(snap["rev"]))
                    self.last_applied_rev = int(snap["rev"])
                rec = frame.get("rec")
                if rec is not None:
                    self.store.apply_replicated(
                        int(rec["rev"]), rec["type"], rec["key"], rec["obj"])
                    self.last_applied_rev = int(rec["rev"])
                f.write(json.dumps(
                    {"ack": self.last_applied_rev}).encode() + b"\n")
                f.flush()
        finally:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------ failure detection

    def _primary_dead(self) -> bool:
        """True only when the primary's address refuses connections for the
        whole grace window.  A successful connect means it's alive (the
        stream drop was transient): resync instead of promoting."""
        deadline = time.monotonic() + self.failover_grace
        while not self._stop.is_set():
            try:
                # liveness probe: a bare connect (no TLS) — an accepting
                # listener means the primary PROCESS is alive even if the
                # TLS handshake would need the full dial
                conn = self._dial(timeout=1.0, tls=False)
                conn.close()
                return False
            except (ConnectionRefusedError, FileNotFoundError):
                pass  # nobody listening: the death signal
            except OSError:
                pass  # unreachable: treat like refused, keep probing
            if time.monotonic() >= deadline:
                return True
            time.sleep(0.1)
        return False
