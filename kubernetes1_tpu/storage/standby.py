"""Warm-standby store: WAL shipping + self-promotion on primary death.

Ref role: the reference's L0 survives member loss because etcd is a raft
quorum and apiservers are just clients (staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go:152,263).  This is the two-member analog: the
standby replays the primary's commit stream into an identical local store
(same revision numbering, own WAL), acks each applied revision — the
primary gates client write-acks on those acks, so an acknowledged write
exists on BOTH disks — and serves NotPrimary to clients until promoted.

Promotion is self-driven: when the replication link drops, the standby
probes the primary's address for `failover_grace` seconds; only a
connection REFUSED verdict (process dead — on a unix socket this is
immediate and unambiguous) promotes.  A transient hiccup with the primary
still listening just reconnects and resyncs.  Split-brain caveat vs raft:
over TCP across hosts a network partition is indistinguishable from death;
a real quorum needs >= 3 members — documented tradeoff, the interface is
shaped so a raft group can replace this later (storage/server.py:21).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Optional, Tuple, Union

from ..client.retry import Backoff
from ..machinery.scheme import Scheme, global_scheme
from ..utils import faultline, flightrec
from .server import StoreServer
from .store import Store


class StandbyServer:
    """Runs a Store fed only by replication + a StoreServer in standby
    mode; promotes itself when the primary is observed dead."""

    def __init__(self, primary_address: Union[str, Tuple[str, int]],
                 serve_address: Union[str, Tuple[str, int]],
                 wal_path: Optional[str] = None,
                 failover_grace: float = 1.0,
                 scheme: Optional[Scheme] = None,
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = "",
                 primary_ca_file: str = "", primary_cert_file: str = "",
                 primary_key_file: str = "",
                 repl_ack_policy: str = "available",
                 rev_offset: int = 0, rev_stride: int = 1):
        self.primary_address = primary_address
        self.failover_grace = failover_grace
        # a TLS-enabled primary (TCP+mTLS deployment) needs a TLS dial for
        # the replication stream — a plaintext handshake would just die
        self._ssl_ctx = None
        if primary_ca_file:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl_ctx.load_verify_locations(cafile=primary_ca_file)
            if primary_cert_file:
                self._ssl_ctx.load_cert_chain(
                    certfile=primary_cert_file,
                    keyfile=primary_key_file or None)
        # a SHARD's standby must keep its shard's revision residue class
        # after promotion (storage/shardmap.py: shard i of N stamps
        # i + k*N) — replicated revs arrive pre-stamped, but the first
        # post-promotion commit must continue the stride, not reset to +1
        self.store = Store(scheme or global_scheme.copy(), wal_path=wal_path,
                           rev_offset=rev_offset, rev_stride=rev_stride)
        self.server = StoreServer(self.store, serve_address,
                                  tls_cert_file=tls_cert_file,
                                  tls_key_file=tls_key_file,
                                  client_ca_file=client_ca_file,
                                  primary=False,
                                  repl_ack_policy=repl_ack_policy)
        self.address = self.server.address
        self.promoted = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # live replication socket, published by _stream_once after a
        # successful dial; None until then (a standby that never reached
        # the primary has nothing to sever in stop())
        self._conn: Optional[socket.socket] = None
        self.last_applied_rev = 0
        # Resync cursor: the last revision this standby ACKED back to the
        # primary (ack written AND flushed).  Reconnects resume from here,
        # not from the store's in-memory revision — under a mid-frame
        # sever a record can be applied while its ack never leaves the
        # socket, and resuming from the applied revision would leave the
        # primary's ack gate waiting on a revision the new session never
        # re-ships.  Re-shipped records the store already holds are
        # deduped by apply_replicated, so resuming low is always safe.
        # Seeded from the local WAL replay (acked in a previous life).
        self.last_acked_rev = self.store.current_revision()
        # ktpu_standby_resyncs_total: replication sessions re-established
        # after a link drop (link flap ≠ promotion — see _primary_dead)
        self.resyncs = 0
        self._sessions = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "StandbyServer":
        self.server.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="store-standby")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # sever the live replication session too: a "stopped" standby
        # whose consumer thread keeps applying and ACKING the primary's
        # commits is still vouching for durability it no longer provides
        # (the same stop-must-sever rule StoreServer.stop() enforces —
        # the primary must see this standby detach NOW)
        conn = self._conn
        if conn is not None:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self.server.stop()

    def promote(self):
        """Standby -> primary.  Its store already holds every acknowledged
        write (the primary's ack gate guarantees it)."""
        if not self.promoted.is_set():
            self.promoted.set()
            self.server.promote()
            flightrec.note("store-standby", flightrec.STANDBY_PROMOTION,
                           rev=self.store.current_revision(),
                           resyncs=self.resyncs)
            print(f"ktpu-store standby PROMOTED at rev "
                  f"{self.store.current_revision()}", flush=True)

    # ----------------------------------------------------------- replication

    def _dial(self, timeout: float = 5.0, tls: bool = True):
        # fault injection on EVERY primary-ward dial — replication stream
        # and liveness probe alike: an injected drop must read as a link
        # flap (ambiguous), never as the refused death signal
        faultline.check("repl.link")
        if isinstance(self.primary_address, str):
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(timeout)
            conn.connect(self.primary_address)
        else:
            conn = socket.create_connection(tuple(self.primary_address),
                                            timeout=timeout)
        if tls and self._ssl_ctx is not None:
            host = self.primary_address if \
                isinstance(self.primary_address, str) \
                else self.primary_address[0]
            conn = self._ssl_ctx.wrap_socket(conn, server_hostname=host)
        return conn

    def _run(self):
        # floor/cap keep the resync cadence near the old fixed 0.1s — the
        # failover grace accounting in _primary_dead samples in wall time
        # and must keep being fed fresh probe results at roughly that rate
        backoff = Backoff(base=0.1, factor=1.5, cap=0.15)
        while not self._stop.is_set() and not self.promoted.is_set():
            try:
                self._stream_once()
            except (OSError, ValueError):
                pass
            if self._stop.is_set() or self.promoted.is_set():
                return
            if self._primary_dead():
                self.promote()
                return
            backoff.sleep(floor=0.05)  # primary alive: transient drop — resync

    def _stream_once(self):
        """One replication session: handshake, then apply records until the
        connection drops.  Resumes from the last ACKED revision (see
        last_acked_rev) — the primary re-ships anything applied-but-
        unacked and apply_replicated dedups it."""
        conn = self._dial()  # _dial carries the repl.link fault site
        self._conn = conn  # published so stop() can sever a live session
        if self._stop.is_set():
            # stop() raced the dial: it may have missed _conn — sever here
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            f = conn.makefile("rwb")
            f.write(json.dumps({
                "id": 1, "method": "replicate",
                "params": {"since_rev": self.last_acked_rev}})
                .encode() + b"\n")
            f.flush()
            line = f.readline()
            if not line:
                return
            resp = json.loads(line)
            if resp.get("error"):
                # primary refused (e.g. itself a standby): wait and retry
                time.sleep(0.2)
                return
            self._sessions += 1
            if self._sessions > 1:
                self.resyncs += 1
            conn.settimeout(None)  # stream blocks until commits arrive
            for line in f:
                # consumer-side fault injection: a drop here is the read
                # half of a mid-frame sever — the session dies, _run
                # reconnects and resyncs from last_acked_rev
                faultline.check("repl.link")
                line = line.strip()
                if not line:
                    continue  # heartbeat
                frame = json.loads(line)
                snap = frame.get("snap")
                if snap is not None:
                    self.store.apply_snapshot(
                        [(k, r, o) for k, r, o in snap["items"]],
                        int(snap["rev"]))
                    self.last_applied_rev = int(snap["rev"])
                rec = frame.get("rec")
                if rec is not None:
                    self.store.apply_replicated(
                        int(rec["rev"]), rec["type"], rec["key"], rec["obj"])
                    self.last_applied_rev = int(rec["rev"])
                f.write(json.dumps(
                    {"ack": self.last_applied_rev}).encode() + b"\n")
                f.flush()
                # flushed, so the primary will see it: safe resume point
                self.last_acked_rev = self.last_applied_rev
        finally:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------ failure detection

    def _primary_dead(self) -> bool:
        """True when the primary's address REFUSES connections for a full,
        uninterrupted grace window — or when NO probe succeeds at all for
        a longer hard window.  A successful connect means it's alive (the
        stream drop was transient): resync instead of promoting.
        AMBIGUOUS failures — timeouts, resets, injected drops on a
        flapping link — are NOT the fast death signal: they reset the
        refused-streak (before this distinction the deadline path
        promoted after ANY failure mix, so a flaky link could split-brain
        the pair without the primary ever dying).  But a host that died
        without an RST — power loss, a partition black-holing SYNs —
        only ever times out, so an uninterrupted streak of failures of
        ANY kind for the hard window promotes too: a genuinely flapping
        link produces interleaved successes, a dead host produces
        none."""
        grace = self.failover_grace
        hard = max(4 * grace, grace + 3.0)
        refused_since: Optional[float] = None
        failing_since: Optional[float] = None
        while not self._stop.is_set():
            try:
                # liveness probe: a bare connect (no TLS) — an accepting
                # listener means the primary PROCESS is alive even if the
                # TLS handshake would need the full dial.  The probe runs
                # through _dial, so injected link faults hit it too —
                # exactly the flap that must NOT promote.
                conn = self._dial(timeout=1.0, tls=False)
                conn.close()
                return False
            except (ConnectionRefusedError, FileNotFoundError):
                refused = True  # nobody listening: the death signal
            except OSError:
                refused = False  # unreachable/reset/injected: ambiguous
            now = time.monotonic()
            if failing_since is None:
                failing_since = now
            if refused:
                if refused_since is None:
                    refused_since = now
                if now - refused_since >= grace:
                    return True
            else:
                refused_since = None
            if now - failing_since >= hard:
                return True  # not one successful connect all window: dead
            time.sleep(0.1)  # ktpulint: ignore[KTPU013] fixed sampling cadence — the refused-streak/hard-window accounting above measures wall-clock windows at this probe rate; jittered backoff would thin the samples the verdict is computed from
        return False
