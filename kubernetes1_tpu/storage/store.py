"""MVCC object store with CAS updates and resumable watch.

This is the L0 storage layer: the TPU-native stand-in for the reference's
etcd3 + watch-cache stack (staging/src/k8s.io/apiserver/pkg/storage/etcd3/
store.go:152 Create, :263 GuaranteedUpdate, :661 Watch; storage/cacher.go).
Design choices relative to the reference:

- One in-process MVCC store *is* the watch cache: every watcher gets its own
  queue fed from a shared, revision-ordered history ring, so N watchers cost
  one event fan-out, exactly what Cacher buys the reference.
- resourceVersion is a global monotonically increasing int64 revision (same
  contract as etcd's mod_revision): lists return the store revision, watches
  resume from any uncompacted revision, resuming below the compaction floor
  raises TooOldResourceVersion (HTTP 410) which forces clients to relist —
  the exact reflector contract (client-go tools/cache/reflector.go:239).
- GuaranteedUpdate is the system's only transaction primitive: read, apply a
  user function, compare-and-swap on resourceVersion, retry on conflict —
  mirroring etcd3 store.go:263's txn loop.
- Optional write-ahead log (JSON lines) gives durability/restart; the control
  plane is otherwise stateless and resumes from LIST+WATCH.

Group commit (the etcd batched-proposal analog): every mutation goes
through an internal commit queue.  The first writer to reach the queue
becomes the leader and drains EVERYTHING queued behind it in one critical
section — N concurrent writers share ONE lock acquisition, ONE
revision-stamped history append run, ONE WAL write+flush(+fsync), and ONE
coalesced fan-out wakeup per watcher/replica/commit-hook (each receives a
LIST of events per notify, not one wakeup per event — a per-commit thread
wakeup measured ~35% of write throughput on the GIL).  `commit_batch`
exposes the same amortization to callers holding N independent ops (the
registry's bulk bind); under the hood a caller batch and concurrent
singleton writers coalesce into the same drain.

WAL durability (`wal_sync`): "batch" (default) issues one flush+fsync per
group commit — an acknowledged write survives a host crash, and the fsync
cost is amortized over every write in the batch; "always" fsyncs per
commit record (strictest, pays one fsync per write even inside a batch);
"none" only flushes to the OS page cache (survives process death, NOT
host/power loss — the pre-group-commit behavior).  Fsync latency lands in
the `ktpu_store_wal_fsync_seconds` histogram.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..machinery import (
    ADDED,
    AlreadyExists,
    ApiError,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    TooOldResourceVersion,
    WatchEvent,
    new_uid,
    now_iso,
)
from ..machinery.scheme import Scheme
from ..utils import faultline, flightrec, invariants, locksan, schedsan
from ..utils.metrics import Histogram

# Keep this many events for watch resume before compaction kicks in.
DEFAULT_HISTORY_LIMIT = 100_000
# Per-watcher delivery queue bound: a consumer this far behind the commit
# stream is wedged, not slow — evict it (it relists) instead of growing the
# queue without limit.  0 disables the bound (internal consumers like the
# watch cache's feed, which is drained by a dedicated pump thread).
DEFAULT_WATCH_QUEUE_LIMIT = 4096
# Replication feeds ride out longer bursts (an evicted standby pays a full
# snapshot resync), but a wedged standby must not pin the commit history.
DEFAULT_REPLICA_QUEUE_LIMIT = 65536
# Commit-timestamp ring bound (watch-lag SLI): revision -> monotonic
# commit stamp for the newest commits.  8192 revisions outlives any
# in-flight watch batch; the informer only ever asks about revs it JUST
# received.
DEFAULT_COMMIT_TS_LIMIT = 8192


class StopUpdate(Exception):
    """Raised by a GuaranteedUpdate callback to abort without error."""


def collection_of(key: str) -> str:
    """"/registry/<resource>/..." -> "<resource>" — THE key-layout parser,
    shared by the store's per-collection index and the watch cache."""
    parts = key.split("/", 3)
    return parts[2] if len(parts) > 2 else ""


def history_index(history, since_rev: int) -> int:
    """First index in a revision-ordered history list whose rev is
    > since_rev (binary search — the history ring can hold 100k entries
    and this runs under the owner's lock)."""
    lo, hi = 0, len(history)
    while lo < hi:
        mid = (lo + hi) // 2
        if history[mid][0] <= since_rev:
            lo = mid + 1
        else:
            hi = mid
    return lo


class Watcher:
    """A single watch stream; iterate to receive WatchEvents; stop() to end.

    Delivery is BOUNDED (queue_limit events; 0 = unbounded): a consumer
    that stops draining — a wedged HTTP client, a stalled informer — is
    EVICTED instead of backing the whole control plane's memory.  Eviction
    ends the stream with `evicted` set so the serving layer answers 410
    Gone and the client relists, the reference cacher's slow-watcher
    contract (storage/cacher.go terminateAllWatchers).

    With buffering=True the watcher starts in replay mode: live pushes are
    buffered while the owner replays history OUTSIDE its lock, then
    flushed in order — so a resume-from-revision neither scans history
    under the hottest lock in the process nor reorders events.

    Delivery is BATCHED: the queue carries LISTS of events, one per group
    commit, so a 50-commit drain wakes each watcher once instead of 50
    times (the consumer-side `_buf` re-flattens; `next_batch_timeout`
    hands whole batches to consumers that can amortize their own per-event
    cost — the chunked-watch serving loop, the remote cacher pump).  The
    queue bound still counts EVENTS, not batches."""

    def __init__(self, owner, prefix: str,
                 queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT,
                 buffering: bool = False):
        self._owner = owner
        self.prefix = prefix
        self._q: "queue.Queue[Optional[List[WatchEvent]]]" = queue.Queue()
        self._limit = queue_limit
        self._qlen = 0  # queued events (not batches), guarded by _plock
        self._buf: "deque[WatchEvent]" = deque()  # consumer thread only
        self._stopped = threading.Event()
        self.evicted = False
        self._pending: Optional[List[WatchEvent]] = [] if buffering else None
        self._plock = locksan.make_lock("storage.Watcher._plock")
        # push-mode delivery hook (set_notify): fired after every queue
        # transition so an event-loop consumer can wake its dispatcher
        # instead of parking a thread in next_batch_timeout
        self._notify: Optional[Callable[[], None]] = None

    def _push(self, ev: WatchEvent):
        """Owner-side: enqueue a single live event (buffered during
        replay).  Cold paths only (history replay); the commit fan-out
        ships whole batches via _push_batch."""
        self._push_batch([ev])

    def _push_batch(self, evs: List[WatchEvent]):
        """Owner-side: enqueue one group commit's events as ONE wakeup."""
        with self._plock:
            if self._pending is not None:
                self._pending.extend(evs)
                return
            self._deliver_locked(evs)

    def _deliver_locked(self, evs: List[WatchEvent]):
        """Must hold _plock: queue the batch, or evict on overflow.  The
        bound is checked against queued EVENTS; a batch may overshoot the
        limit by its own length (bounded by the largest group commit)."""
        if self._stopped.is_set():
            return
        if self._limit and self._qlen >= self._limit:
            self._evict_locked()
            return
        self._qlen += len(evs)
        self._q.put(evs)
        if self._notify is not None:
            self._notify()  # non-blocking by contract (see set_notify)

    def _evict_locked(self, note: bool = True):
        """Must hold _plock: end this stream as a slow/stale consumer.
        Queued events still drain; then the consumer sees the stream end
        with `evicted` set and answers 410.  note=False skips the
        slow-consumer counter (reseed evictions are not the client's
        fault and are tracked separately)."""
        if self._stopped.is_set():
            return
        self.evicted = True
        self._stopped.set()
        self._q.put(None)
        if self._notify is not None:
            self._notify()
        if note:
            self._owner._note_watch_eviction()

    def _evict(self, note: bool = True):
        with self._plock:
            self._evict_locked(note)

    def _replay_entries(self, entries):
        """Deliver one history snapshot (taken under an owner's lock, but
        filtered and delivered outside it); the watcher keeps buffering
        live pushes until _go_live.  _plock is taken per event, NOT
        across the whole replay: a commit's fan-out blocks on _plock
        while holding the owner's lock, so one watcher resuming far
        behind must not convoy every writer."""
        for _rev, typ, key, obj in entries:
            if self._stopped.is_set():
                break
            if key.startswith(self.prefix):
                with self._plock:
                    self._deliver_locked([WatchEvent(typ, obj)])

    def _go_live(self):
        """Flush the live events buffered during replay(s), in arrival
        order — per-source revision order preserved."""
        with self._plock:
            for ev in self._pending:
                self._deliver_locked([ev])
            self._pending = None

    def _replay_and_go_live(self, entries):
        """Replay one owner's snapshot, then go live (the single-source
        path; the sharded fan-in replays N snapshots before going live)."""
        self._replay_entries(entries)
        self._go_live()

    def stop(self):
        if not self._stopped.is_set():
            self._stopped.set()
            self._q.put(None)
            if self._notify is not None:
                self._notify()
            self._owner._remove_watcher(self)

    def __iter__(self):
        return self

    def __next__(self) -> WatchEvent:
        ev = self._next_event(None)
        if ev is None:
            raise StopIteration
        return ev

    def _take_batch(self, batch: List[WatchEvent]):
        """Consumer-side: account a batch popped off the queue."""
        with self._plock:
            self._qlen -= len(batch)
        self._buf.extend(batch)

    def _next_event(self, timeout: Optional[float]) -> Optional[WatchEvent]:
        if self._buf:
            return self._buf.popleft()
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is None:
            return None
        self._take_batch(item)
        return self._buf.popleft()

    def next_timeout(self, timeout: float) -> Optional[WatchEvent]:
        """Non-raising get with timeout; returns None on timeout/stop."""
        return self._next_event(timeout)

    def next_batch_timeout(self, timeout: float) -> Optional[List[WatchEvent]]:
        """Everything deliverable right now as ONE list (at least one
        event), or None on timeout/stream-end.  Consumers that amortize
        per-event cost (one flush per batch on the chunked-watch wire, one
        cache-lock acquisition in the remote pump) drain with this."""
        if not self._buf:
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                return None
            if item is None:
                return None
            self._take_batch(item)
        # opportunistically drain whatever else is already queued — without
        # blocking, and preserving the end-of-stream sentinel for the next
        # call (None is always the queue's final item)
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)
                break
            self._take_batch(nxt)
        out = list(self._buf)
        self._buf.clear()
        return out

    def set_notify(self, fn: Optional[Callable[[], None]]):
        """Install a delivery hook for PUSH-mode consumers (the event-loop
        watch dispatcher): called after every queue transition — batch
        delivered, eviction, stop — possibly from the owner's commit path
        UNDER its lock, so ``fn`` must never block (the dispatcher's hook
        is a deque append + non-blocking self-pipe write).  Installing a
        hook fires it once immediately so anything already queued is
        observed; pull consumers (next_batch_timeout) never set one."""
        with self._plock:
            self._notify = fn
        if fn is not None:
            fn()

    def next_batch_nowait(self) -> Optional[List[WatchEvent]]:
        """Non-blocking twin of next_batch_timeout — the cacher batch
        cursor an event-loop connection state machine drains on notify:
        everything deliverable right now as one list, ``[]`` when nothing
        is queued, ``None`` on stream end (eviction or stop).  Same
        consumer-thread contract and the same end-of-stream sentinel
        preservation as the blocking variant."""
        if not self._buf:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return []
            if item is None:
                return None
            self._take_batch(item)
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)
                break
            self._take_batch(nxt)
        out = list(self._buf)
        self._buf.clear()
        return out

    def progress_rv(self) -> Optional[int]:
        """Consumer-thread only: a resume revision SAFE to hand the client
        as a progress bookmark, or None when no safe answer exists right
        now (events queued but undelivered — a bookmark would leap past
        them, and a cut before their delivery would silently gap the
        resumed stream).

        Safety argument, order-sensitive: the owner's revision is read
        FIRST through its own lock (Cacher._cond / Store._lock) — every
        event <= that revision was pushed inside the same critical
        section that published it.  The queue-empty check runs AFTER: if
        nothing is queued now, everything pushed before the revision read
        has already been handed to this consumer, so every event <= rev
        destined for this stream is on the wire.  Events landing between
        the two reads have rev > the answer and simply make it
        conservative.  This is what lets an IDLE informer's resume point
        ride the cache head (above the compaction floor) instead of
        aging into a 410 full relist."""
        owner = self._owner
        fn = (getattr(owner, "current_cached_revision", None)
              or getattr(owner, "current_revision", None))
        if fn is None:
            return None
        rev = fn()
        if not rev:
            return None
        with self._plock:
            if self._qlen or self._pending is not None:
                return None
        if self._buf or not self._q.empty():
            # _buf: consumer-side remainder; _q non-empty: a batch (or the
            # end sentinel) raced in after the qlen check — skip this tick
            return None
        return rev


class ReplicaFeed:
    """A standby's subscription to the primary's commit stream: a queue of
    (rev, type, key, obj) records, optionally preceded by a full snapshot
    (set when the standby's since_rev predates the history floor).

    Bounded like Watcher: a standby that stops draining is cut loose
    (`evicted` set, stream ends) rather than pinning the commit backlog in
    RAM — it reconnects and resyncs, via snapshot if it fell past the
    history floor.

    Batched like Watcher too: one queue wakeup per group commit, with the
    records re-flattened consumer-side (`next_timeout`) or handed out
    whole (`next_batch_timeout` — the replication sender writes a batch's
    records in one socket flush)."""

    def __init__(self, queue_limit: int = DEFAULT_REPLICA_QUEUE_LIMIT):
        self._q: "queue.Queue[Optional[List[tuple]]]" = queue.Queue()
        self._limit = queue_limit
        self._qlen = 0  # queued records, guarded by _qlock
        self._qlock = locksan.make_lock("storage.ReplicaFeed._qlock")
        self._buf: "deque[tuple]" = deque()  # consumer thread only
        self._stopped = threading.Event()
        self.evicted = False
        self.snapshot: Optional[tuple] = None  # (items, rev) or None

    def _push(self, rec: tuple):
        self._push_batch([rec])

    def _push_batch(self, recs: List[tuple]):
        if self._stopped.is_set():
            return
        with self._qlock:
            if self._limit and self._qlen >= self._limit:
                self.evicted = True
                self._stopped.set()
                self._q.put(None)
                return
            self._qlen += len(recs)
        self._q.put(recs)

    def _take_batch(self, batch: List[tuple]):
        with self._qlock:
            self._qlen -= len(batch)
        self._buf.extend(batch)

    def next_timeout(self, timeout: float) -> Optional[tuple]:
        if self._buf:
            return self._buf.popleft()
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is None:
            return None
        self._take_batch(item)
        return self._buf.popleft()

    def next_batch_timeout(self, timeout: float) -> Optional[List[tuple]]:
        """All records deliverable right now, or None on timeout/end."""
        if not self._buf:
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                return None
            if item is None:
                return None
            self._take_batch(item)
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)
                break
            self._take_batch(nxt)
        out = list(self._buf)
        self._buf.clear()
        return out

    def stop(self, store: "Store"):
        self._stopped.set()
        self._q.put(None)
        store._remove_replica(self)


class _PendingCommit:
    """One writer's queued mutation: `fn` runs under the store lock inside
    the leader's drain; the outcome (result or exception) travels back to
    the enqueuing thread through this record."""

    __slots__ = ("fn", "event", "result", "exc")

    def __init__(self, fn: Callable):
        self.fn = fn
        self.event = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


class Store:
    """MVCC store with group commit.  `wal_sync` is the crash-durability
    policy: "batch" (default) = one flush+fsync per group commit, so every
    acknowledged write is on disk and the fsync amortizes across the
    batch; "always" = fsync per commit record; "none" = flush to the OS
    page cache only (survives process death, not host/power loss)."""

    def __init__(
        self,
        scheme: Scheme,
        wal_path: Optional[str] = None,
        history_limit: int = DEFAULT_HISTORY_LIMIT,
        wal_sync: str = "batch",
        rev_offset: int = 0,
        rev_stride: int = 1,
    ):
        # Sharded deployments (storage/shardmap.py) give shard i of N
        # rev_offset=i, rev_stride=N: this store then stamps revisions
        # i+N, i+2N, ... — per-shard strictly monotonic, globally unique
        # across the shard set, and the owning shard is recoverable as
        # rev % N.  The default (0, 1) is today's 1, 2, 3, ... exactly.
        if rev_stride < 1 or not 0 <= rev_offset < rev_stride:
            raise ValueError(
                f"rev_offset must be in [0, rev_stride); got offset="
                f"{rev_offset} stride={rev_stride}")
        self.rev_offset = rev_offset
        self.rev_stride = rev_stride
        self._scheme = scheme
        self._lock = threading.RLock()  # ktpulint: ignore[KTPU007] hottest lock in the process (every MVCC op); sanitizer tracking would tax every request
        self._data: Dict[str, Tuple[int, Dict[str, Any]]] = {}  # key -> (rev, encoded obj)
        # Per-collection index: first path segment after /registry/ -> keys.
        # list("/registry/pods/...") must not scan (or sort) every event and
        # endpoint in the store — full-store sorted scans made pod-create
        # latency grow linearly with cluster history at 30k-pod density.
        self._by_collection: Dict[str, set] = {}
        self._rev = rev_offset
        # History ring for watch resume: list of (rev, type, key, encoded obj)
        self._history: List[Tuple[int, str, str, Dict[str, Any]]] = []
        self._history_limit = history_limit
        self._compacted_rev = 0  # watches must start > this
        self._watchers: List[Watcher] = []
        self._replicas: List["ReplicaFeed"] = []
        # slow-consumer eviction counters (surfaced as
        # ktpu_watch_slow_consumer_evictions_total on /metrics).  The
        # watcher counter has its own leaf lock because evictions can fire
        # from a replay thread that does NOT hold self._lock.
        self.watch_evictions = 0
        self.replica_evictions = 0
        self._stats_lock = locksan.make_lock("storage.Store._stats_lock")
        # synchronous commit sinks (the in-process watch cache): called as
        # fn(records) — one call per GROUP COMMIT with the batch's
        # [(rev, typ, key, obj), ...] — inside the commit critical section,
        # so a sink is NEVER behind the store: no feed queue, no pump-thread
        # wakeup per commit (measured ~35% of write throughput on the
        # GIL), no freshness wait on reads
        self._commit_hooks: List[Callable] = []
        # Group-commit queue: writers enqueue a pending op and contend on
        # _commit_mu; the winner drains the whole queue in one critical
        # section (see module docstring).  Lock order: _commit_mu -> _lock.
        self._commit_q: List["_PendingCommit"] = []
        self._commit_q_lock = locksan.make_lock("storage.Store._commit_q_lock")
        self._commit_mu = locksan.make_lock("storage.Store._commit_mu")
        self._batch_records: Optional[List[tuple]] = None  # drain context
        # write-path economics, surfaced on the apiserver's /metrics:
        # commits/batches = group-commit occupancy; wakeups/events < 1.0
        # means fan-out is coalescing (the BENCH_r06 acceptance metric)
        self.commit_count = 0
        self.commit_batches = 0
        self.watch_wakeups = 0
        self.watch_events = 0
        # deletion-path economics (ktpu_store_delete_batch_occupancy):
        # delete ops shipped through caller batches (commit_batch) vs the
        # batches that carried them — occupancy ~1.0 means the hot delete
        # callers (gang teardown, podgc, eviction) are NOT batching
        self.delete_batch_ops = 0
        self.delete_batches = 0
        # Watch-lag SLI (obs plane): every group commit stamps ONE
        # monotonic timestamp shared by its records; the serving layer
        # ships it on watch-lag bookmark frames so informers can export
        # delivered-at minus committed-at.  CLOCK_MONOTONIC is system-
        # wide on Linux, so the stamp is comparable across processes on
        # one host — the single-box deployment every bench and chaos
        # schedule runs; cross-host lag would need a synced wall clock.
        self._commit_ts: Dict[int, float] = {}
        self._commit_ts_order: deque = deque()
        self.wal_fsync_seconds = Histogram(
            "ktpu_store_wal_fsync_seconds",
            "WAL fsync latency per group commit",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                     0.025, 0.05, 0.1, 0.25, 1.0))
        if wal_sync not in ("none", "batch", "always"):
            raise ValueError(f"wal_sync must be none|batch|always, got {wal_sync!r}")
        self.wal_sync = wal_sync
        self._wal_path = wal_path
        self._wal = None
        # torn-tail repairs on open (ktpu_wal_torn_tail_repairs_total): a
        # crash mid-WAL-write leaves a partial record at the tail; replay
        # detects it (CRC/parse) and truncates it away — the write was
        # never acknowledged (the batch's writers all error on WAL
        # failure), so dropping it loses nothing
        self.wal_torn_tail_repairs = 0
        # mid-file damage is NOT a torn tail: when valid records follow a
        # bad line, truncating there would discard acknowledged durable
        # state — replay skips the bad line(s), keeps everything after,
        # and counts them here (loud log; the tail rule stays truncate)
        self.wal_corrupt_records_skipped = 0
        # failed WAL writes on a LIVE store roll the torn prefix back out
        # (see _wal_emit) so later batches don't append after garbage
        self.wal_write_rollbacks = 0
        if wal_path:
            self._replay_wal(wal_path)
            # block-buffered binary: the group-commit drain flushes (and
            # fsyncs, per wal_sync) explicitly ONCE per batch — line
            # buffering would pay a write syscall per record again; bytes
            # (not text) so the fault injector can tear mid-record exactly
            # like a crash does
            self._wal = open(wal_path, "ab")

    # ---------------------------------------------------------------- helpers

    def current_revision(self) -> int:
        with self._lock:
            return self._rev

    @staticmethod
    def _wal_frame(rec: dict) -> bytes:
        """One CRC-framed WAL record: `<crc32 hex8>:<json>\\n`.  The CRC
        covers the JSON payload, so replay can tell a torn tail (crash or
        full disk mid-write) from a complete record without trusting the
        JSON parser alone."""
        payload = json.dumps(rec).encode()
        return b"%08x:" % zlib.crc32(payload) + payload + b"\n"

    @staticmethod
    def _parse_wal_frame(line: bytes) -> Optional[dict]:
        """Decode one WAL line; None means torn/corrupt.  Legacy lines
        (bare JSON, pre-CRC WALs) stay replayable — their torn tails are
        caught by the parse alone, as before."""
        line = line.strip()
        try:
            if line.startswith(b"{"):
                rec = json.loads(line)
            else:
                crc, sep, payload = line.partition(b":")
                if not sep or len(crc) != 8:
                    return None
                if int(crc, 16) != zlib.crc32(payload):
                    return None
                rec = json.loads(payload)
            # a record missing its fields is as unusable as an unparsable
            # one — surface both as torn
            rec["rev"], rec["type"], rec["key"], rec["obj"]
            return rec
        except (ValueError, KeyError, TypeError):
            return None

    def _replay_wal(self, path: str):  # ktpulint: ignore[KTPU001] construction-time, pre-concurrency
        if not os.path.exists(path):
            return
        # offset where the current run of unparsable lines began; a run
        # still open at EOF is the torn TAIL (truncate — those bytes are a
        # record that was never acked); a run with valid records AFTER it
        # is mid-file damage (skip it, keep the later acked records —
        # truncating there would silently discard durable state)
        bad_start: Optional[int] = None
        bad_lines = 0
        with open(path, "rb") as f:
            while True:
                start = f.tell()
                line = f.readline()
                if not line:
                    break
                if not line.strip():
                    continue  # blank padding line: harmless
                rec = self._parse_wal_frame(line)
                if rec is None:
                    if bad_start is None:
                        bad_start = start
                    bad_lines += 1
                    continue
                if bad_start is not None:
                    self.wal_corrupt_records_skipped += bad_lines
                    print(f"store: WAL CORRUPTION mid-file — skipped "
                          f"{bad_lines} unreadable line(s) at offset "
                          f"{bad_start} of {path}; later records are "
                          f"intact and were replayed (NOT truncating — "
                          f"that would discard acknowledged state)",
                          flush=True)
                    bad_start = None
                    bad_lines = 0
                rev, typ, key, obj = (rec["rev"], rec["type"], rec["key"],
                                      rec["obj"])
                self._rev = max(self._rev, rev)
                if typ == "NOP":  # snapshot revision pin, no data
                    continue
                if typ == DELETED:
                    self._data.pop(key, None)
                    coll = self._by_collection.get(self._collection_of(key))
                    if coll is not None:
                        coll.discard(key)
                else:
                    self._data[key] = (rev, obj)
                    self._by_collection.setdefault(
                        self._collection_of(key), set()
                    ).add(key)
        if bad_start is not None:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(bad_start)
            self.wal_torn_tail_repairs += 1
            flightrec.note("store", flightrec.WAL_REPAIR, op="torn_tail",
                           path=path, bytes=size - bad_start)
            print(f"store: WAL torn tail repaired — truncated "
                  f"{size - bad_start} byte(s) at offset {bad_start} of "
                  f"{path} (replayed to rev {self._rev}; a standby resync "
                  f"covers anything newer)", flush=True)
        # A crash can land after the last record's bytes but before its
        # trailing newline: the record parses (the CRC covers the JSON,
        # not the \n) and replays as acked state — but reopening in
        # append mode would weld the NEXT frame onto the same line,
        # turning two durable records into one unparsable line a later
        # replay would truncate or skip.  Restore the frame terminator
        # before any append can happen.
        if os.path.getsize(path) > 0:
            with open(path, "r+b") as f:
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        # Watches cannot resume across restart below the replayed revision.
        self._compacted_rev = self._rev

    # ------------------------------------------------------- group commit

    def _run_commit(self, fn: Callable):
        """Route one mutation through the group-commit queue.  `fn` runs
        under the store lock (precondition checks + _commit_locked calls)
        inside whichever thread wins the leader election; its return value
        (or exception) comes back to this caller.  Writers blocked on
        _commit_mu while a leader drains are exactly the batch the next
        drain picks up — the gather needs no timer."""
        p = _PendingCommit(fn)
        with self._commit_q_lock:
            self._commit_q.append(p)
        # Yield the GIL once between enqueue and leader election: a
        # concurrent burst's writers all enqueue BEFORE the first drain
        # runs, so the drain picks them up as one batch.  Without this,
        # CPU-bound writers each complete enqueue->drain inside one GIL
        # quantum and every "batch" is a singleton (measured on a
        # 16-writer create storm: occupancy 1.0 -> 6.6, fan-out wakeups
        # per event 1.0 -> 0.15).  sleep(0) is a bare yield — microseconds
        # for a solo writer, dwarfed by the JSON encode it just did.
        time.sleep(0)
        # the enqueue->election window above is the group-commit race the
        # interleaving sanitizer exists to stress: a preemption here must
        # only grow batches, never lose a writer's commit
        schedsan.preempt("store.commit.leader")
        with self._commit_mu:
            # a prior leader may have already committed us while we were
            # blocked on the mutex; only drain if there's still work
            if not p.event.is_set():
                self._drain_commits()  # ktpulint: ignore[KTPU017] group commit: the leader holds _commit_mu across the batched WAL fsync BY DESIGN — followers queueing behind exactly this flush is what amortizes it
        if p.exc is not None:
            raise p.exc
        return p.result

    def _drain_commits(self):
        """Leader-side (holds _commit_mu): commit every queued pending in
        ONE critical section — one lock acquisition, one revision-stamp
        run, one WAL write+flush(+fsync), one coalesced fan-out."""
        with self._commit_q_lock:
            pendings, self._commit_q = self._commit_q, []
        if not pendings:
            return
        records: List[tuple] = []
        wal_exc: Optional[BaseException] = None
        try:
            with self._lock:
                self._batch_records = records
                try:
                    for p in pendings:
                        try:
                            p.result = p.fn()
                        except BaseException as e:  # outcome -> the writer
                            p.exc = e
                finally:
                    self._batch_records = None
                if records:
                    try:
                        self._write_wal_locked(records)  # ktpulint: ignore[KTPU017] WAL-before-visibility: the durability write MUST complete under the MVCC lock or a reader could see a revision the log never recorded
                    except OSError as e:  # ENOSPC/EIO: durability lost
                        wal_exc = e
                    # fan out even on WAL failure: the in-memory MVCC state
                    # WAS mutated above, and watchers/the sync-fed cache
                    # must stay coherent with it — a skipped fan-out would
                    # serve stale reads at the wrong revision forever
                    self._stamp_commit_ts_locked(records)
                    self._fanout_batch_locked(records)
                    self.commit_count += len(records)
                    self.commit_batches += 1
        finally:
            # ALWAYS wake the writers; on a WAL failure NO writer in the
            # batch may ack success — the write is applied in memory but
            # not durable, and a silent ack would lie to the client
            for p in pendings:
                if wal_exc is not None and p.exc is None:
                    p.exc = ApiError(
                        f"write applied but WAL persistence failed: "
                        f"{wal_exc}")
                p.event.set()

    def _commit_locked(self, typ: str, key: str, obj: Dict[str, Any]):
        """Must hold lock, inside a drain: assigns the next revision and
        applies to data/history.  WAL + fan-out happen ONCE per batch at
        the end of the drain (the record lands in _batch_records)."""
        self._rev += self.rev_stride
        rev = self._rev
        # two-level copy: never re-stamp a dict already committed to history
        # or handed to a watcher (delete passes the stored dict back in here)
        obj = {**obj, "metadata": dict(obj.get("metadata") or {})}
        obj["metadata"]["resourceVersion"] = str(rev)
        if typ == DELETED:
            self._data.pop(key, None)
            coll = self._by_collection.get(self._collection_of(key))
            if coll is not None:
                coll.discard(key)
        else:
            self._data[key] = (rev, obj)
            self._by_collection.setdefault(self._collection_of(key), set()).add(key)
        self._history.append((rev, typ, key, obj))
        if len(self._history) > self._history_limit:
            drop = len(self._history) - self._history_limit
            self._compacted_rev = self._history[drop - 1][0]
            del self._history[:drop]
        self._batch_records.append((rev, typ, key, obj))
        return rev, obj

    def _stamp_commit_ts_locked(self, records: List[tuple]):
        """Must hold lock: one monotonic stamp per group commit, shared
        by every record in the batch (the batch IS one commit event —
        per-record clock reads would just measure the loop)."""
        ts = time.monotonic()
        for rev, _typ, _key, _obj in records:
            self._commit_ts[rev] = ts
            self._commit_ts_order.append(rev)
        while len(self._commit_ts_order) > DEFAULT_COMMIT_TS_LIMIT:
            self._commit_ts.pop(self._commit_ts_order.popleft(), None)

    def commit_ts_of(self, rev: int) -> Optional[float]:
        """Monotonic commit stamp for a recent revision (None once it has
        aged out of the ring or for pre-restart revisions).  Lock-free
        read: dict lookups are atomic under the GIL and a raced insert
        only means a one-call-late answer."""
        return self._commit_ts.get(rev)

    def _wal_emit(self, data: bytes):
        """Write framed WAL bytes, subject to fault injection: an injected
        `truncate` persists a strict PREFIX (the torn record a crash
        leaves) and then raises — the batch's writers all error (no
        silent ack).  A LIVE store that survives the failure (ENOSPC, an
        injected tear) must not keep appending after the torn bytes —
        later acked records would land beyond garbage and replay-on-open
        could not tell them from a torn tail — so the failure path rolls
        the file back to the pre-write offset.  Only a CRASH mid-write
        leaves a torn tail for the open-time repair."""
        exc: Optional[Exception] = None
        if faultline.active():
            data, exc = faultline.filter_bytes("wal.write", data)
        pre = self._wal.tell()
        try:
            if data:
                self._wal.write(data)
            if exc is not None:
                self._wal.flush()  # the torn bytes land, as in a crash...
                raise exc
            # flush INSIDE the guard: the WAL is block-buffered, so a small
            # write() merely buffers and the real I/O error (ENOSPC, EIO)
            # surfaces here — an unguarded flush left torn bytes the next
            # batch appended after, corrupting an acked record on replay
            self._wal.flush()
        except OSError:
            self._rollback_wal(pre)  # ...then the live store repairs them
            raise

    def _rollback_wal(self, pre: int):
        """Best-effort truncate back to the pre-write offset after a
        failed WAL write.  If the rollback itself fails, replay-on-open
        still copes: a trailing run of garbage truncates as a torn tail,
        and garbage followed by later valid records is skipped without
        truncation."""
        try:
            try:
                self._wal.flush()
            except OSError:
                pass  # buffered remainder may be what failed; truncate anyway
            os.ftruncate(self._wal.fileno(), pre)
            self._wal.seek(pre)
            self.wal_write_rollbacks += 1
            flightrec.note("store", flightrec.WAL_REPAIR, op="rollback",
                           offset=pre)
        except OSError as e:
            print(f"store: WAL rollback after failed write ALSO failed "
                  f"({e}) — open-time replay will skip or truncate the "
                  f"damage", flush=True)

    def _write_wal_locked(self, records: List[tuple]):
        """Must hold lock: one WAL write+flush per batch; fsync per the
        wal_sync policy (see class docstring)."""
        if not self._wal:
            return
        if self.wal_sync == "always":
            for rev, typ, key, obj in records:
                self._wal_emit(self._wal_frame(
                    {"rev": rev, "type": typ, "key": key, "obj": obj}))
                t0 = time.monotonic()
                os.fsync(self._wal.fileno())
                self.wal_fsync_seconds.observe(time.monotonic() - t0)
            return
        self._wal_emit(b"".join(
            self._wal_frame({"rev": rev, "type": typ, "key": key,
                             "obj": obj})
            for rev, typ, key, obj in records))
        if self.wal_sync == "batch":
            t0 = time.monotonic()
            os.fsync(self._wal.fileno())
            self.wal_fsync_seconds.observe(time.monotonic() - t0)

    def _fanout_batch_locked(self, records: List[tuple]):
        """Must hold lock: ONE wakeup per matching watcher/replica/hook for
        the whole batch — events are shared across watchers AND delivered
        as lists, so N watchers x M commits cost N pushes, not N*M (used by
        local commits AND replicated applies — the delivery rules must not
        drift between them)."""
        # probe: batches must reach the fan-out in commit order — two
        # leaders draining concurrently or a reordered replicated apply
        # would move this store's revision stream backwards
        invariants.rev_monotonic("store.fanout",
                                 invariants.stream_of(self, "store"),
                                 records[0][0])
        events = [(key, WatchEvent(typ, obj))
                  for _rev, typ, key, obj in records]
        evicted = False
        for w in self._watchers:
            evs = [ev for key, ev in events if key.startswith(w.prefix)]
            if evs:
                invariants.rev_monotonic(
                    "store.watch", invariants.stream_of(w, "watcher"),
                    records[0][0])
                w._push_batch(evs)
                self.watch_wakeups += 1
                self.watch_events += len(evs)
            evicted = evicted or w.evicted
        if evicted:
            # prune lazily: eviction fires inside the fan-out loop, where
            # removing from the list being iterated would skip watchers
            self._watchers = [w for w in self._watchers if not w.evicted]
        if self._replicas:
            for r in self._replicas:
                r._push_batch(records)
            dead = [r for r in self._replicas if r.evicted]
            if dead:
                self.replica_evictions += len(dead)
                self._replicas = [r for r in self._replicas if not r.evicted]
        for hook in self._commit_hooks:
            hook(records)

    def add_commit_hook(self, fn: Callable):
        """Register a synchronous commit sink, called as fn(records) with
        one [(rev, typ, key, obj), ...] list per group commit (see
        _commit_hooks)."""
        with self._lock:
            self._commit_hooks.append(fn)

    def remove_commit_hook(self, fn: Callable):
        with self._lock:
            try:
                self._commit_hooks.remove(fn)
            except ValueError:
                pass

    def _note_watch_eviction(self):
        with self._stats_lock:
            self.watch_evictions += 1

    def _decode(self, obj: Dict[str, Any]):
        return self._scheme.decode(obj)

    # ------------------------------------------------------------- operations

    def create(self, key: str, obj) -> Any:
        """Create; fails with AlreadyExists. Stamps uid/creationTimestamp."""
        meta = obj.metadata
        if not meta.uid:
            meta.uid = new_uid()
        if not meta.creation_timestamp:
            meta.creation_timestamp = now_iso()
        encoded = self._scheme.encode(obj)

        def commit():
            if key in self._data:
                raise AlreadyExists(f"{key} already exists")
            _, stored = self._commit_locked(ADDED, key, encoded)
            return stored

        # decode OUTSIDE the commit path (here and in get/update_cas/
        # delete): committed dicts are immutable, and response decoding
        # under the hottest lock in the process serialized every reader
        # and writer behind each individual request's deserialization
        return self._decode(self._run_commit(commit))

    def get(self, key: str) -> Any:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            raw = ent[1]
        return self._decode(raw)

    def get_or_none(self, key: str):
        try:
            return self.get(key)
        except NotFound:
            return None

    _collection_of = staticmethod(collection_of)

    def list_raw(self, prefix: str) -> Tuple[List[Tuple[str, int, Dict[str, Any]]], int]:
        """Raw (key, rev, encoded obj) entries under prefix + the store
        revision.  No decode: the watch cache and the HTTP read path
        consume the committed wire form directly (committed dicts are
        immutable by the _commit_locked copy contract)."""
        with self._lock:
            coll = self._collection_of(prefix)
            if coll:
                keys = self._by_collection.get(coll)
                if keys is None:
                    return [], self._rev
                keys = sorted(keys)
            else:
                # cross-collection prefix ("/registry/"): the watch cache
                # seeds its whole view in one list — full scan is the point
                keys = sorted(self._data)
            entries = [
                (key,) + self._data[key]
                for key in keys
                if key.startswith(prefix) and key in self._data
            ]
            return entries, self._rev

    def list(self, prefix: str) -> Tuple[List[Any], int]:
        """All objects under prefix + the store revision for watch resume.
        Raw entries are snapshotted under the lock and decoded AFTER
        release — decoding is the expensive half of a list, and doing it
        under the lock serialized every read against every write."""
        entries, rev = self.list_raw(prefix)
        return [self._decode(obj) for _key, _rev, obj in entries], rev

    def update_cas(self, key: str, obj) -> Any:
        """Single compare-and-swap using obj.metadata.resource_version."""
        encoded = self._scheme.encode(obj)
        expect = obj.metadata.resource_version

        def commit():
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev, _ = ent
            if expect and str(cur_rev) != expect:
                raise Conflict(
                    f"{key}: resourceVersion mismatch (have {cur_rev}, want {expect})"
                )
            _, stored = self._commit_locked(MODIFIED, key, encoded)
            return stored

        return self._decode(self._run_commit(commit))

    def guaranteed_update(self, key: str, update_fn: Callable[[Any], Any]) -> Any:
        """Read-modify-CAS retry loop (ref: etcd3 store.go:263).

        update_fn receives a fresh decoded copy and returns the new object
        (mutating in place is fine — decode builds fresh containers at
        every level, including a deep-copied Unstructured.content, so the
        copy never aliases committed state; see Scheme.decode).  Raise
        StopUpdate to abort cleanly.
        """
        while True:
            cur = self.get(key)
            updated = update_fn(cur)
            if updated is None:
                updated = cur
            try:
                return self.update_cas(key, updated)
            except Conflict:
                continue

    def delete(self, key: str, expect_rv: str = "") -> Any:
        def commit():
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev, obj = ent
            if expect_rv and str(cur_rev) != expect_rv:
                raise Conflict(f"{key}: resourceVersion mismatch")
            _, stored = self._commit_locked(DELETED, key, obj)
            return stored

        return self._decode(self._run_commit(commit))

    # ------------------------------------------------------- batch operations

    def get_raw_many(self, keys: List[str]) -> List[Optional[Dict[str, Any]]]:
        """Encoded wire dicts for N keys (None where absent) under ONE lock
        acquisition — the read half of a read-modify-CAS batch (bulk
        bind)."""
        with self._lock:
            out = []
            for key in keys:
                ent = self._data.get(key)
                out.append(None if ent is None else ent[1])
            return out

    def commit_batch(self, ops: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Group-commit N independent mutations as ONE batch.

        Each op is {"op": "create"|"update_cas"|"delete", "key": str,
        "obj": <encoded wire dict> (create/update_cas),
        "expect_rv": str (optional CAS guard)} — the ENCODED form on both
        sides, so the wire protocol and the registry share one shape and
        the batch path never decodes under the lock.

        Returns one {"obj": committed encoded dict} or {"error": ApiError}
        per op, same order.  This is amortization, not a transaction: ops
        fail independently (a bulk bind's members bind independently), and
        successful ops commit even when neighbors fail.  The whole batch
        shares one lock acquisition, one revision-stamp run, one WAL
        flush(+fsync), and one fan-out wakeup; concurrent callers coalesce
        into the same drain."""
        def commit():
            out: List[Dict[str, Any]] = []
            ndel = 0
            for op in ops:
                if op.get("op") == "delete":
                    ndel += 1
                try:
                    out.append({"obj": self._apply_op_locked(op)})
                except ApiError as e:
                    out.append({"error": e})
            if ndel:
                # caller-batch deletion occupancy (under _lock, inside the
                # drain): ops per delete-carrying batch — the deletion
                # half's analog of commit_count/commit_batches
                self.delete_batch_ops += ndel
                self.delete_batches += 1
            return out

        return self._run_commit(commit)

    def _apply_op_locked(self, op: Dict[str, Any]) -> Dict[str, Any]:
        """Must hold lock, inside a drain: one batch op -> committed dict."""
        kind, key = op.get("op"), op["key"]
        if kind == "create":
            if key in self._data:
                raise AlreadyExists(f"{key} already exists")
            obj = op["obj"]
            meta = obj.get("metadata") or {}
            # same server-side stamping as create(): the batch path must
            # produce byte-identical committed state and watch frames
            if not meta.get("uid") or not meta.get("creationTimestamp"):
                obj = {**obj, "metadata": dict(meta)}
                if not obj["metadata"].get("uid"):
                    obj["metadata"]["uid"] = new_uid()
                if not obj["metadata"].get("creationTimestamp"):
                    obj["metadata"]["creationTimestamp"] = now_iso()
            _, stored = self._commit_locked(ADDED, key, obj)
            return stored
        if kind == "update_cas":
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev = ent[0]
            expect = op.get("expect_rv", "")
            if expect and str(cur_rev) != expect:
                raise Conflict(
                    f"{key}: resourceVersion mismatch "
                    f"(have {cur_rev}, want {expect})")
            _, stored = self._commit_locked(MODIFIED, key, op["obj"])
            return stored
        if kind == "delete":
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev, obj = ent
            expect = op.get("expect_rv", "")
            if expect and str(cur_rev) != expect:
                raise Conflict(f"{key}: resourceVersion mismatch")
            _, stored = self._commit_locked(DELETED, key, obj)
            return stored
        raise ApiError(f"unknown batch op {kind!r}")

    # ------------------------------------------------------------------ watch

    def watch(self, prefix: str, since_rev: int = 0,
              queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT) -> Watcher:
        """Watch events for keys under prefix with rev > since_rev.

        since_rev==0 means "from now".  Resuming below the compaction floor
        raises TooOldResourceVersion — the client must relist.  The replay
        slice is located by binary search and delivered OUTSIDE the store
        lock (the watcher buffers live pushes until the replay lands), so
        registering a resuming watcher no longer scans up to
        history_limit entries under the hottest lock in the process.
        """
        w = Watcher(self, prefix, queue_limit=queue_limit,
                    buffering=bool(since_rev))
        replay = self.attach_watcher(w, since_rev)
        if since_rev:
            w._replay_and_go_live(replay)
        return w

    def attach_watcher(self, w: Watcher, since_rev: int = 0):
        """Register an externally-built Watcher (the sharded fan-in path:
        one Watcher shared across N shard stores feeds one queue with
        zero pump threads) and return the history slice the CALLER must
        replay outside the lock — empty when since_rev==0.  A resuming
        watcher must be constructed with buffering=True and go live only
        after every replay has been delivered."""
        with self._lock:
            if since_rev and since_rev < self._compacted_rev:
                raise TooOldResourceVersion(
                    f"revision {since_rev} compacted "
                    f"(floor {self._compacted_rev})")
            replay = (self._history[history_index(self._history, since_rev):]
                      if since_rev else [])
            self._watchers.append(w)
        return replay

    def _remove_watcher(self, w: Watcher):
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass

    # ------------------------------------------------------------ replication
    #
    # WAL shipping to a warm standby (the role etcd's raft quorum plays for
    # the reference — staging/src/k8s.io/apiserver/pkg/storage/etcd3/
    # store.go:263: apiservers are stateless clients of a store that
    # survives member loss).  The feed carries the full commit record
    # (rev, type, key, obj) — exactly the WAL line — so a standby replays
    # commits verbatim and its store is revision-identical to the primary.

    def replication_feed(self, since_rev: int = 0,
                         queue_limit: int = DEFAULT_REPLICA_QUEUE_LIMIT,
                         ) -> "ReplicaFeed":
        """Subscribe to commit records > since_rev.  If since_rev is below
        the history floor the feed carries a snapshot first (the standby's
        state is too old to catch up incrementally)."""
        with self._lock:
            feed = ReplicaFeed(queue_limit=queue_limit)
            if since_rev < self._compacted_rev:
                # too old: full-state snapshot at the current revision,
                # then stream from here
                feed.snapshot = ([(k, rev, obj)
                                  for k, (rev, obj) in self._data.items()],
                                 self._rev)
            else:
                # binary-search the start instead of scanning the whole
                # ring under the lock; the slice holds only rev > since_rev
                start = history_index(self._history, since_rev)
                for rec in self._history[start:]:
                    feed._push(rec)
            if feed.evicted:
                # overflowed during the replay itself (standby too far
                # behind): count it now and never register the dead feed
                self.replica_evictions += 1
            else:
                self._replicas.append(feed)
            return feed

    def _remove_replica(self, feed: "ReplicaFeed"):
        with self._lock:
            try:
                self._replicas.remove(feed)
            except ValueError:
                pass

    def apply_replicated(self, rev: int, typ: str, key: str,
                         obj: Dict[str, Any]):
        """Standby-side: apply a shipped commit record verbatim, preserving
        the primary's revision numbering (the standby must be able to serve
        watches resuming from primary-issued resourceVersions after
        promotion).  Fans out to local watchers and the local WAL."""
        with self._lock:
            if rev <= self._rev:
                return  # replay overlap after reconnect: already applied
            self._rev = rev
            if typ == DELETED:
                self._data.pop(key, None)
                coll = self._by_collection.get(self._collection_of(key))
                if coll is not None:
                    coll.discard(key)
            else:
                self._data[key] = (rev, obj)
                self._by_collection.setdefault(
                    self._collection_of(key), set()).add(key)
            self._history.append((rev, typ, key, obj))
            if len(self._history) > self._history_limit:
                drop = len(self._history) - self._history_limit
                self._compacted_rev = self._history[drop - 1][0]
                del self._history[:drop]
            records = [(rev, typ, key, obj)]
            wal_exc: Optional[BaseException] = None
            try:
                self._write_wal_locked(records)  # ktpulint: ignore[KTPU017] WAL-before-visibility on the replication apply path: same rule as _drain_commits
            except OSError as e:  # injected tear / ENOSPC
                wal_exc = e
            # fan out even on WAL failure (same rule as _drain_commits):
            # the in-memory state WAS mutated above and local views must
            # stay coherent with it
            self._stamp_commit_ts_locked(records)
            self._fanout_batch_locked(records)
            self.commit_count += 1
            self.commit_batches += 1
            if wal_exc is not None:
                # surface to the replication consumer: it must NOT ack
                # this record as durable; the reconnect-resync (and a
                # torn-tail repair on restart) covers the gap
                raise wal_exc

    def apply_snapshot(self, items, rev: int):
        """Standby-side: replace local state with a primary snapshot."""
        with self._lock:
            self._data = {k: (r, obj) for k, r, obj in items}
            self._by_collection = {}
            for k in self._data:
                self._by_collection.setdefault(
                    self._collection_of(k), set()).add(k)
            self._rev = rev
            self._history = []
            self._compacted_rev = rev
            if self._wal:
                # rewrite the WAL as a snapshot so a standby restart
                # replays to the same state
                self._wal.close()
                self._wal = open(self._wal_path, "wb")
                for k, (r, obj) in self._data.items():
                    self._wal.write(self._wal_frame(
                        {"rev": r, "type": ADDED, "key": k, "obj": obj}))
                # deletes can make the store revision exceed every live
                # item's rev; a NOP record pins it for WAL replay
                self._wal.write(self._wal_frame(
                    {"rev": rev, "type": "NOP", "key": "", "obj": {}}))
                self._wal.flush()
                if self.wal_sync != "none":
                    os.fsync(self._wal.fileno())

    def compact(self, keep_last: int = 1000):
        with self._lock:
            if len(self._history) > keep_last:
                drop = len(self._history) - keep_last
                self._compacted_rev = self._history[drop - 1][0]
                del self._history[:drop]

    def close(self):
        # snapshot under the lock, stop OUTSIDE it: a sharded fan-in
        # watcher's stop() detaches from EVERY shard, and holding this
        # shard's lock while touching a sibling's would order locks
        # across shards (deadlock-prone against a concurrent close)
        with self._lock:
            watchers = list(self._watchers)
            wal, self._wal = self._wal, None
        for w in watchers:
            w.stop()
        if wal:
            wal.close()
