"""MVCC object store with CAS updates and resumable watch.

This is the L0 storage layer: the TPU-native stand-in for the reference's
etcd3 + watch-cache stack (staging/src/k8s.io/apiserver/pkg/storage/etcd3/
store.go:152 Create, :263 GuaranteedUpdate, :661 Watch; storage/cacher.go).
Design choices relative to the reference:

- One in-process MVCC store *is* the watch cache: every watcher gets its own
  queue fed from a shared, revision-ordered history ring, so N watchers cost
  one event fan-out, exactly what Cacher buys the reference.
- resourceVersion is a global monotonically increasing int64 revision (same
  contract as etcd's mod_revision): lists return the store revision, watches
  resume from any uncompacted revision, resuming below the compaction floor
  raises TooOldResourceVersion (HTTP 410) which forces clients to relist —
  the exact reflector contract (client-go tools/cache/reflector.go:239).
- GuaranteedUpdate is the system's only transaction primitive: read, apply a
  user function, compare-and-swap on resourceVersion, retry on conflict —
  mirroring etcd3 store.go:263's txn loop.
- Optional write-ahead log (JSON lines) gives durability/restart; the control
  plane is otherwise stateless and resumes from LIST+WATCH.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..machinery import (
    ADDED,
    AlreadyExists,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    TooOldResourceVersion,
    WatchEvent,
    new_uid,
    now_iso,
)
from ..machinery.scheme import Scheme

# Keep this many events for watch resume before compaction kicks in.
DEFAULT_HISTORY_LIMIT = 100_000


class StopUpdate(Exception):
    """Raised by a GuaranteedUpdate callback to abort without error."""


class Watcher:
    """A single watch stream; iterate to receive WatchEvents; stop() to end."""

    def __init__(self, store: "Store", prefix: str):
        self._store = store
        self.prefix = prefix
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = threading.Event()

    def _push(self, ev: WatchEvent):
        if not self._stopped.is_set():
            self._q.put(ev)

    def stop(self):
        if not self._stopped.is_set():
            self._stopped.set()
            self._q.put(None)
            self._store._remove_watcher(self)

    def __iter__(self):
        return self

    def __next__(self) -> WatchEvent:
        ev = self._q.get()
        if ev is None:
            raise StopIteration
        return ev

    def next_timeout(self, timeout: float) -> Optional[WatchEvent]:
        """Non-raising get with timeout; returns None on timeout/stop."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev


class ReplicaFeed:
    """A standby's subscription to the primary's commit stream: a queue of
    (rev, type, key, obj) records, optionally preceded by a full snapshot
    (set when the standby's since_rev predates the history floor)."""

    def __init__(self):
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._stopped = threading.Event()
        self.snapshot: Optional[tuple] = None  # (items, rev) or None

    def _push(self, rec: tuple):
        if not self._stopped.is_set():
            self._q.put(rec)

    def next_timeout(self, timeout: float) -> Optional[tuple]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self, store: "Store"):
        self._stopped.set()
        self._q.put(None)
        store._remove_replica(self)


class Store:
    def __init__(
        self,
        scheme: Scheme,
        wal_path: Optional[str] = None,
        history_limit: int = DEFAULT_HISTORY_LIMIT,
    ):
        self._scheme = scheme
        self._lock = threading.RLock()  # ktpulint: ignore[KTPU007] hottest lock in the process (every MVCC op); sanitizer tracking would tax every request
        self._data: Dict[str, Tuple[int, Dict[str, Any]]] = {}  # key -> (rev, encoded obj)
        # Per-collection index: first path segment after /registry/ -> keys.
        # list("/registry/pods/...") must not scan (or sort) every event and
        # endpoint in the store — full-store sorted scans made pod-create
        # latency grow linearly with cluster history at 30k-pod density.
        self._by_collection: Dict[str, set] = {}
        self._rev = 0
        # History ring for watch resume: list of (rev, type, key, encoded obj)
        self._history: List[Tuple[int, str, str, Dict[str, Any]]] = []
        self._history_limit = history_limit
        self._compacted_rev = 0  # watches must start > this
        self._watchers: List[Watcher] = []
        self._replicas: List["ReplicaFeed"] = []
        self._wal_path = wal_path
        self._wal = None
        if wal_path:
            self._replay_wal(wal_path)
            self._wal = open(wal_path, "a", buffering=1)

    # ---------------------------------------------------------------- helpers

    def current_revision(self) -> int:
        with self._lock:
            return self._rev

    def _replay_wal(self, path: str):  # ktpulint: ignore[KTPU001] construction-time, pre-concurrency
        if not os.path.exists(path):
            return
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                rev, typ, key, obj = rec["rev"], rec["type"], rec["key"], rec["obj"]
                self._rev = max(self._rev, rev)
                if typ == "NOP":  # snapshot revision pin, no data
                    continue
                if typ == DELETED:
                    self._data.pop(key, None)
                    coll = self._by_collection.get(self._collection_of(key))
                    if coll is not None:
                        coll.discard(key)
                else:
                    self._data[key] = (rev, obj)
                    self._by_collection.setdefault(
                        self._collection_of(key), set()
                    ).add(key)
        # Watches cannot resume across restart below the replayed revision.
        self._compacted_rev = self._rev

    def _commit_locked(self, typ: str, key: str, obj: Dict[str, Any]):
        """Must hold lock. Assigns the next revision and fans out."""
        self._rev += 1
        rev = self._rev
        # two-level copy: never re-stamp a dict already committed to history
        # or handed to a watcher (delete passes the stored dict back in here)
        obj = {**obj, "metadata": dict(obj.get("metadata") or {})}
        obj["metadata"]["resourceVersion"] = str(rev)
        if typ == DELETED:
            self._data.pop(key, None)
            coll = self._by_collection.get(self._collection_of(key))
            if coll is not None:
                coll.discard(key)
        else:
            self._data[key] = (rev, obj)
            self._by_collection.setdefault(self._collection_of(key), set()).add(key)
        self._history.append((rev, typ, key, obj))
        if len(self._history) > self._history_limit:
            drop = len(self._history) - self._history_limit
            self._compacted_rev = self._history[drop - 1][0]
            del self._history[:drop]
        if self._wal:
            self._wal.write(
                json.dumps({"rev": rev, "type": typ, "key": key, "obj": obj}) + "\n"
            )
        event = WatchEvent(typ, obj)
        for w in self._watchers:
            if key.startswith(w.prefix):
                w._push(event)
        for r in self._replicas:
            r._push((rev, typ, key, obj))
        return rev, obj

    def _decode(self, obj: Dict[str, Any]):
        return self._scheme.decode(obj)

    # ------------------------------------------------------------- operations

    def create(self, key: str, obj) -> Any:
        """Create; fails with AlreadyExists. Stamps uid/creationTimestamp."""
        meta = obj.metadata
        if not meta.uid:
            meta.uid = new_uid()
        if not meta.creation_timestamp:
            meta.creation_timestamp = now_iso()
        encoded = self._scheme.encode(obj)
        with self._lock:
            if key in self._data:
                raise AlreadyExists(f"{key} already exists")
            _, stored = self._commit_locked(ADDED, key, encoded)
            return self._decode(stored)

    def get(self, key: str) -> Any:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            return self._decode(ent[1])

    def get_or_none(self, key: str):
        try:
            return self.get(key)
        except NotFound:
            return None

    @staticmethod
    def _collection_of(key: str) -> str:
        # "/registry/<resource>/..." -> "<resource>"
        parts = key.split("/", 3)
        return parts[2] if len(parts) > 2 else ""

    def list(self, prefix: str) -> Tuple[List[Any], int]:
        """All objects under prefix + the store revision for watch resume."""
        with self._lock:
            keys = self._by_collection.get(self._collection_of(prefix))
            if keys is None:
                return [], self._rev
            items = [
                self._decode(self._data[key][1])
                for key in sorted(keys)
                if key.startswith(prefix) and key in self._data
            ]
            return items, self._rev

    def update_cas(self, key: str, obj) -> Any:
        """Single compare-and-swap using obj.metadata.resource_version."""
        encoded = self._scheme.encode(obj)
        expect = obj.metadata.resource_version
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev, _ = ent
            if expect and str(cur_rev) != expect:
                raise Conflict(
                    f"{key}: resourceVersion mismatch (have {cur_rev}, want {expect})"
                )
            _, stored = self._commit_locked(MODIFIED, key, encoded)
            return self._decode(stored)

    def guaranteed_update(self, key: str, update_fn: Callable[[Any], Any]) -> Any:
        """Read-modify-CAS retry loop (ref: etcd3 store.go:263).

        update_fn receives a fresh decoded copy and returns the new object
        (mutating in place is fine).  Raise StopUpdate to abort cleanly.
        """
        while True:
            cur = self.get(key)
            updated = update_fn(cur)
            if updated is None:
                updated = cur
            try:
                return self.update_cas(key, updated)
            except Conflict:
                continue

    def delete(self, key: str, expect_rv: str = "") -> Any:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                raise NotFound(f"{key} not found")
            cur_rev, obj = ent
            if expect_rv and str(cur_rev) != expect_rv:
                raise Conflict(f"{key}: resourceVersion mismatch")
            _, stored = self._commit_locked(DELETED, key, obj)
            return self._decode(stored)

    # ------------------------------------------------------------------ watch

    def watch(self, prefix: str, since_rev: int = 0) -> Watcher:
        """Watch events for keys under prefix with rev > since_rev.

        since_rev==0 means "from now".  Resuming below the compaction floor
        raises TooOldResourceVersion — the client must relist.
        """
        with self._lock:
            if since_rev and since_rev < self._compacted_rev:
                raise TooOldResourceVersion(
                    f"revision {since_rev} compacted (floor {self._compacted_rev})"
                )
            w = Watcher(self, prefix)
            if since_rev:
                for rev, typ, key, obj in self._history:
                    if rev > since_rev and key.startswith(prefix):
                        w._push(WatchEvent(typ, obj))
            self._watchers.append(w)
            return w

    def _remove_watcher(self, w: Watcher):
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass

    # ------------------------------------------------------------ replication
    #
    # WAL shipping to a warm standby (the role etcd's raft quorum plays for
    # the reference — staging/src/k8s.io/apiserver/pkg/storage/etcd3/
    # store.go:263: apiservers are stateless clients of a store that
    # survives member loss).  The feed carries the full commit record
    # (rev, type, key, obj) — exactly the WAL line — so a standby replays
    # commits verbatim and its store is revision-identical to the primary.

    def replication_feed(self, since_rev: int = 0) -> "ReplicaFeed":
        """Subscribe to commit records > since_rev.  If since_rev is below
        the history floor the feed carries a snapshot first (the standby's
        state is too old to catch up incrementally)."""
        with self._lock:
            feed = ReplicaFeed()
            if since_rev < self._compacted_rev:
                # too old: full-state snapshot at the current revision,
                # then stream from here
                feed.snapshot = ([(k, rev, obj)
                                  for k, (rev, obj) in self._data.items()],
                                 self._rev)
            else:
                for rev, typ, key, obj in self._history:
                    if rev > since_rev:
                        feed._push((rev, typ, key, obj))
            self._replicas.append(feed)
            return feed

    def _remove_replica(self, feed: "ReplicaFeed"):
        with self._lock:
            try:
                self._replicas.remove(feed)
            except ValueError:
                pass

    def apply_replicated(self, rev: int, typ: str, key: str,
                         obj: Dict[str, Any]):
        """Standby-side: apply a shipped commit record verbatim, preserving
        the primary's revision numbering (the standby must be able to serve
        watches resuming from primary-issued resourceVersions after
        promotion).  Fans out to local watchers and the local WAL."""
        with self._lock:
            if rev <= self._rev:
                return  # replay overlap after reconnect: already applied
            self._rev = rev
            if typ == DELETED:
                self._data.pop(key, None)
                coll = self._by_collection.get(self._collection_of(key))
                if coll is not None:
                    coll.discard(key)
            else:
                self._data[key] = (rev, obj)
                self._by_collection.setdefault(
                    self._collection_of(key), set()).add(key)
            self._history.append((rev, typ, key, obj))
            if len(self._history) > self._history_limit:
                drop = len(self._history) - self._history_limit
                self._compacted_rev = self._history[drop - 1][0]
                del self._history[:drop]
            if self._wal:
                self._wal.write(json.dumps(
                    {"rev": rev, "type": typ, "key": key, "obj": obj}) + "\n")
            event = WatchEvent(typ, obj)
            for w in self._watchers:
                if key.startswith(w.prefix):
                    w._push(event)

    def apply_snapshot(self, items, rev: int):
        """Standby-side: replace local state with a primary snapshot."""
        with self._lock:
            self._data = {k: (r, obj) for k, r, obj in items}
            self._by_collection = {}
            for k in self._data:
                self._by_collection.setdefault(
                    self._collection_of(k), set()).add(k)
            self._rev = rev
            self._history = []
            self._compacted_rev = rev
            if self._wal:
                # rewrite the WAL as a snapshot so a standby restart
                # replays to the same state
                self._wal.close()
                self._wal = open(self._wal_path, "w", buffering=1)
                for k, (r, obj) in self._data.items():
                    self._wal.write(json.dumps(
                        {"rev": r, "type": ADDED, "key": k,
                         "obj": obj}) + "\n")
                # deletes can make the store revision exceed every live
                # item's rev; a NOP record pins it for WAL replay
                self._wal.write(json.dumps(
                    {"rev": rev, "type": "NOP", "key": "", "obj": {}})
                    + "\n")

    def compact(self, keep_last: int = 1000):
        with self._lock:
            if len(self._history) > keep_last:
                drop = len(self._history) - keep_last
                self._compacted_rev = self._history[drop - 1][0]
                del self._history[:drop]

    def close(self):
        with self._lock:
            for w in list(self._watchers):
                w.stop()
            if self._wal:
                self._wal.close()
                self._wal = None
