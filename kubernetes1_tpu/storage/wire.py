"""Store wire framing: newline-JSON (legacy, default) and negotiated
length-prefixed binary frames.

The store<->apiserver link historically spoke one line of JSON per
request/response/watch frame.  That stays the dial-time default and the
universal fallback; a client that wants the binary fast path sends ONE
ordinary JSON request first::

    {"id": 0, "method": "negotiate",
     "params": {"codec": "pybin1", "framing": "lp1"}}

A server that supports the codec answers ``{"id": 0, "result": {"codec":
..., "framing": "lp1"}}`` and the connection switches — every subsequent
byte in BOTH directions is length-prefixed binary::

    frame   = <len: 4-byte big-endian unsigned> <payload: len bytes>
    payload = codec.encode(envelope dict)

Any other answer (an old server's "unknown store method" error, an
unsupported codec, a standby's NotPrimary) leaves the connection in
newline-JSON mode — old client <-> new server and new client <-> old
server both interoperate with zero configuration.

Failure semantics the framing buys:

- A frame is dispatched only when COMPLETE: a send that dies mid-frame
  (injected sever, killed peer) leaves a prefix the receiver can never
  mistake for a request, so mid-send failures are safely retryable.
- A receiver hitting EOF after a partial header or mid-payload raises
  ``FrameTruncated`` (a ConnectionError) — the torn frame surfaces as a
  clean transport error through the existing retry/reseed machinery,
  never as a hang or a half-parsed object.

``BinFramer.send_payloads`` assembles a batch's frames into one buffer
and ships it with a single write+flush — a group-commit watch fan-out
batch is one syscall on the wire.  Outbound bytes run through the
``store.rpc``/``store.watch`` faultline sites (``filter_bytes``), so
seeded chaos can tear frames at the exact byte granularity a crash
would.  The legacy newline-JSON protocol stays implemented inline in
storage/server.py and storage/remote.py (a framer of None), unchanged
byte for byte.
"""

from __future__ import annotations

import struct
from typing import Any, List

from ..machinery.codec import get_codec
from ..utils import faultline

FRAMING_LP1 = "lp1"
# Sanity cap on a declared frame length: a 30k-pod LIST response is tens
# of MB; anything near this cap is a corrupt header, not a payload.
MAX_FRAME_BYTES = 1 << 30
_LEN = struct.Struct(">I")

NEGOTIATE_METHOD = "negotiate"


class FrameTruncated(ConnectionError):
    """EOF (or an injected sever) mid-frame: the peer died or cut the
    stream between a frame's header and its last byte."""


class BinFramer:
    """Length-prefixed frames carrying codec payloads (see module doc)."""

    binary = True

    def __init__(self, f, codec_id: str, site: str = "store.rpc"):
        self._f = f
        self._codec = get_codec(codec_id)
        self.codec_id = codec_id
        self.site = site

    # ------------------------------------------------------------- sending

    def send(self, obj: Any) -> None:
        self.send_payloads([self._codec.encode(obj)])

    def send_payloads(self, payloads: List[bytes]) -> None:
        """Frame N pre-encoded payloads and ship them as ONE buffer, one
        write+flush — batch frame assembly is the fan-out fast path."""
        buf = bytearray()
        for p in payloads:
            buf += _LEN.pack(len(p))
            buf += p
        data = bytes(buf)
        exc = None
        if faultline.active():
            data, exc = faultline.filter_bytes(self.site, data)
        if data:
            self._f.write(data)
        self._f.flush()
        if exc is not None:
            raise exc

    # ----------------------------------------------------------- receiving

    def _read_exact(self, n: int, header: bool) -> bytes:
        data = self._f.read(n)
        if not data and header:
            # EOF at a frame boundary: the clean-close case
            raise BrokenPipeError("peer closed the connection")
        if len(data) != n:
            raise FrameTruncated(
                f"truncated frame on {self.site}: wanted {n} bytes, "
                f"got {len(data)}")
        return data

    def recv(self) -> dict:
        """One decoded frame.  Raises BrokenPipeError on clean close,
        FrameTruncated on a torn frame, CodecError on a corrupt payload."""
        (n,) = _LEN.unpack(self._read_exact(_LEN.size, header=True))
        if not 0 < n <= MAX_FRAME_BYTES:
            raise FrameTruncated(
                f"insane frame length {n} on {self.site}: corrupt header")
        return self._codec.decode(self._read_exact(n, header=False))


def negotiate_request(codec_id: str) -> dict:
    return {"id": 0, "method": NEGOTIATE_METHOD,
            "params": {"codec": codec_id, "framing": FRAMING_LP1}}


def negotiation_accepted(resp: dict, codec_id: str) -> bool:
    """True when the server's answer commits the connection to binary
    framing under `codec_id` — anything else means stay on JSON."""
    res = resp.get("result") or {}
    return (not resp.get("error")
            and res.get("codec") == codec_id
            and res.get("framing") == FRAMING_LP1)
