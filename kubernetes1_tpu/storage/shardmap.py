"""Store sharding: partition /registry/ across N stores, keep the watch.

The control plane's last serial structure after scheduler sharding (PR 9)
was the single store process: one commit queue, one WAL fsync stream, one
watch-history ring.  This module splits it the way production Kubernetes
splits events into a separate etcd — except the partition is a hash over
the full object key, so even ONE hot collection (30k pods) spreads across
every shard and the bind rate scales with shard count.

Layout
------
- ``ShardMap``: crc32(key) % N.  Deterministic and config-free, so every
  apiserver in a multi-apiserver deployment routes identically.
- ``ShardedStore``: the existing Store interface over N shard stores —
  in-process ``Store`` instances or per-shard ``RemoteStore`` clients
  (each with its own primary,standby failover list).  Key ops route to
  one shard; prefix ops (LIST, watch) merge across all of them.
- ``ShardedCacher``: one watch cache per shard (sync-fed in process,
  progress-notify pump per shard against remote stores) behind the
  Cacher read surface.
- ``FanInWatcher``: ONE delivery queue fed by every shard.  In-process
  shards share the Watcher object directly (zero pump threads — each
  shard's commit fan-out pushes into the same bounded queue); remote
  shards get one forwarding pump per stream.

Revision contract (the heart of the design)
-------------------------------------------
Shard i of N stamps revisions ``i + k*N`` (``Store(rev_offset=i,
rev_stride=N)``): per-shard revision order stays STRICT and dense-enough,
revisions are globally unique across the shard set, and ``rev % N``
recovers the owning shard from any object's resourceVersion.  Cross-shard
ordering is deliberately NOT defined — the multi-etcd Kubernetes posture:
clients may observe shard B's rev 7 before shard A's rev 4.

Merged LISTs return a COMPOSITE resourceVersion ``"r0.r1.…"`` (one part
per shard, ``format_rv``/``parse_rv``); resuming a merged watch from a
composite resumes every shard at exactly its own position — no gaps, no
duplicates.  Merged watch streams additionally carry BOOKMARK frames (the
Kubernetes watch-bookmark analog, emitted by the apiserver's serve loop
from ``FanInWatcher.bookmark_rv()``) so informers always hold a composite
to resume from.  A single-int resume R is accepted with the only
semantics one shard's revision can prove:

- ``R == 0``        → from now, every shard;
- ``0 < R < N``     → replay everything (no event can have rev <= R);
- ``R >= N``        → events after R on R's own shard (``R % N``), from
  now on the others.

``shards == 1`` degenerates exactly to today's behavior: offsets (0, 1)
stamp 1, 2, 3, …, composite rvs collapse to plain ints, and bookmarks are
not emitted (``emit_bookmarks`` False) — byte-identical wire frames.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..machinery import TooOldResourceVersion
from ..utils import locksan
from .cacher import Cacher
from .store import DEFAULT_WATCH_QUEUE_LIMIT, Store, Watcher


def parse_shard_addresses(address: str) -> List[str]:
    """';'-separated shard groups, each group a comma-separated
    primary,standby failover list for ONE shard (what RemoteStore's
    multi-endpoint parser consumes).  A single group (no ';') is the
    unsharded store address unchanged."""
    return [g.strip() for g in str(address).split(";") if g.strip()]


def format_rv(revs: Sequence[int]) -> str:
    """Composite resourceVersion: one part per shard, shard order.  A
    single shard collapses to the plain int string clients always saw."""
    return ".".join(str(int(r)) for r in revs)


def parse_rv(value) -> Union[int, Tuple[int, ...]]:
    """A wire resourceVersion -> int (plain) or tuple (composite).
    Raises ValueError on garbage — callers surface it as BadRequest."""
    if value is None:
        return 0
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if not s:
        return 0
    if "." in s:
        return tuple(int(p) for p in s.split("."))
    return int(s)


class ShardMap:
    """Static key partition.  crc32 over the full ``/registry/...`` key:
    hot collections spread across every shard (the property the bind-rate
    scaling target needs), and the map is pure arithmetic — every
    apiserver and every restart routes identically with zero config."""

    def __init__(self, shards: int):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.shards = shards

    def shard_of_key(self, key: str) -> int:
        if self.shards == 1:
            return 0
        return (zlib.crc32(key.encode()) & 0xFFFFFFFF) % self.shards


class FanInWatcher(Watcher):
    """One bounded delivery queue fed by N shards; duck-types Watcher for
    every consumer (the apiserver's chunked-watch loop included).

    In-process shards push into it directly (the shared-object fan-in:
    the Watcher is registered in each shard's watcher list, so a group
    commit on any shard is one `_push_batch` — no pump thread, no extra
    wakeup).  Remote shards stream through one forwarding pump each; a
    dead sub-stream marks the merged stream `closed` so the serving layer
    ends it and the client relists — a merged stream missing one shard
    can never again be gap-free.

    `bookmark_rv()` (consumer thread only) is the composite of per-shard
    delivered positions, seeded from the resume plan and advanced as
    events are handed to the consumer — exactly what a client must
    present to resume with no gaps and no duplicates."""

    def __init__(self, owner, prefix: str, shards: int,
                 queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT,
                 buffering: bool = False):
        super().__init__(owner, prefix, queue_limit=queue_limit,
                         buffering=buffering)
        self._nshards = shards
        self._positions = [0] * shards  # consumer thread only (see class doc)
        self.closed = False
        # bookmarks only mean something when streams actually merge; a
        # 1-shard facade must stay byte-identical to the plain path
        self.emit_bookmarks = shards > 1
        self._subs: List[Any] = []  # remote sub-watchers (stop() severs them)

    # ------------------------------------------------------------ positions

    def seed_positions(self, revs: Sequence[int]):
        self._positions = [int(r) for r in revs]

    def _take_batch(self, batch):
        super()._take_batch(batch)
        for ev in batch:
            try:
                rev = int((ev.object.get("metadata") or {})
                          .get("resourceVersion") or 0)
            except (TypeError, ValueError):
                continue
            if rev > 0:
                i = rev % self._nshards
                if rev > self._positions[i]:
                    self._positions[i] = rev

    def bookmark_rv(self) -> str:
        return format_rv(self._positions)

    def progress_rv(self):
        """Progress bookmarks carry a PLAIN int rv — meaningful only for
        the 1-shard facade (where it equals that shard's revision).
        Merged streams already keep idle clients fresh with composite
        bookmark_rv() heartbeats; a single int would gap their resume."""
        if self._nshards > 1:
            return None
        return super().progress_rv()

    # -------------------------------------------------------- remote shards

    def add_remote(self, sub):
        """Adopt one remote shard's stream: a pump forwards its batches
        into the shared queue (per-shard order preserved — one pump per
        stream, arrival order within it)."""
        self._subs.append(sub)
        t = threading.Thread(target=self._pump_remote, args=(sub,),
                             daemon=True, name="store-shard-watch-pump")
        t.start()

    def _pump_remote(self, sub):
        while not self._stopped.is_set():
            evs = sub.next_batch_timeout(1.0)
            if evs is None:
                if getattr(sub, "closed", False) or sub._stopped.is_set():
                    break
                continue
            if evs:  # [] is a progress-only wakeup: nothing to forward
                self._push_batch(evs)
        # sub-stream over: if the merged stream is still live, it just
        # lost a shard and can never be gap-free again — end it so the
        # consumer relists (the cacher-reseed contract, per shard)
        self.closed = True
        with self._plock:
            if not self._stopped.is_set():
                self._stopped.set()
                self._q.put(None)

    def stop(self):
        for sub in self._subs:
            try:
                sub.stop()
            except OSError:  # remote stream teardown: socket already dead
                pass
        super().stop()


class ShardedStore:
    """The Store interface over N shard stores (see module docstring).

    Key ops route by ShardMap; prefix ops merge.  `commit_batch` /
    `get_raw_many` group by shard — each shard still amortizes its
    sub-batch through ONE group commit, and a cross-shard batch stays
    what the single-store batch always was: amortization, NOT a
    transaction (per-op outcomes, neighbors commit independently)."""

    def __init__(self, stores: Sequence, shard_map: Optional[ShardMap] = None):
        if not stores:
            raise ValueError("ShardedStore needs at least one shard")
        self._stores = list(stores)
        self.map = shard_map or ShardMap(len(self._stores))
        if self.map.shards != len(self._stores):
            raise ValueError(
                f"shard map arity {self.map.shards} != stores "
                f"{len(self._stores)}")
        self.shards = len(self._stores)
        self._stats_lock = locksan.make_lock("storage.ShardedStore._stats_lock")
        self._fanin_evictions = 0
        # caller-level delete batches: one delete:batch scattered over N
        # shards is ONE caller batch, not N — summing the shards' own
        # per-sub-batch counts would under-report the amortization the
        # occupancy gauge exists to show
        self._delete_batches = 0
        # concurrent fan-out pays only when sub-calls leave the GIL (a
        # remote shard's socket round-trip + its WAL fsync); in-process
        # shards are pure lock+memory work where extra threads just add
        # scheduling overhead
        self._parallel = any(not hasattr(s, "attach_watcher")
                             for s in self._stores)

    @property
    def shard_stores(self) -> List[Any]:
        """The underlying shard stores, shard order (bench/metrics)."""
        return list(self._stores)

    def _shard_for(self, key: str):
        return self._stores[self.map.shard_of_key(key)]

    # ---------------------------------------------------------- aggregates

    def _sum_attr(self, name: str):
        vals = [getattr(s, name) for s in self._stores if hasattr(s, name)]
        return sum(vals) if vals else None

    @property
    def commit_count(self):
        return self._sum_attr("commit_count")

    @property
    def commit_batches(self):
        return self._sum_attr("commit_batches")

    @property
    def delete_batch_ops(self):
        return self._sum_attr("delete_batch_ops") or 0

    @property
    def delete_batches(self):
        with self._stats_lock:
            return self._delete_batches

    @property
    def watch_wakeups(self):
        return self._sum_attr("watch_wakeups") or 0

    @property
    def watch_events(self):
        return self._sum_attr("watch_events") or 0

    @property
    def watch_evictions(self):
        with self._stats_lock:
            own = self._fanin_evictions
        return (self._sum_attr("watch_evictions") or 0) + own

    @property
    def wal_torn_tail_repairs(self):
        return self._sum_attr("wal_torn_tail_repairs") or 0

    @property
    def wal_fsync_seconds(self):
        """Shard 0's histogram (the /metrics render slot); per-shard
        detail lives in the bench `store_shards` block and each shard
        process's own /metrics."""
        return self._stores[0].wal_fsync_seconds

    # ------------------------------------------------------------ routing

    def current_revision(self) -> int:
        """Highest exposed revision across the shard set — a monitoring
        number; freshness logic is per-shard (see ShardedCacher)."""
        return max(s.current_revision() for s in self._stores)

    def commit_ts_of(self, rev: int):
        """Monotonic commit stamp of a revision, routed by the stride
        contract: rev % N names the owning shard (watch-lag SLI — lag is
        PER-SHARD, never cross-shard clock math)."""
        st = self._stores[rev % self.shards]
        fn = getattr(st, "commit_ts_of", None)
        return fn(rev) if fn is not None else None

    def shard_revisions(self) -> List[int]:
        return [s.current_revision() for s in self._stores]

    def create(self, key: str, obj):
        return self._shard_for(key).create(key, obj)

    def get(self, key: str):
        return self._shard_for(key).get(key)

    def get_or_none(self, key: str):
        return self._shard_for(key).get_or_none(key)

    def update_cas(self, key: str, obj):
        return self._shard_for(key).update_cas(key, obj)

    def guaranteed_update(self, key: str, update_fn: Callable):
        return self._shard_for(key).guaranteed_update(key, update_fn)

    def delete(self, key: str, expect_rv: str = ""):
        return self._shard_for(key).delete(key, expect_rv)

    def compact(self, keep_last: int = 1000):
        for s in self._stores:
            s.compact(keep_last)

    def close(self):
        for s in self._stores:
            s.close()

    def add_commit_hook(self, fn: Callable):
        for s in self._stores:
            s.add_commit_hook(fn)

    def remove_commit_hook(self, fn: Callable):
        for s in self._stores:
            s.remove_commit_hook(fn)

    # -------------------------------------------------------------- reads

    def _fan_out(self, calls: List[Callable[[], Any]]) -> List[Any]:
        """Run per-shard sub-calls CONCURRENTLY and return their results
        in order (re-raising the first failure).  Against remote shards
        each sub-call is a socket round-trip (plus the shard's own
        commit latency — WAL fsync included); running them serially
        makes a cross-shard batch pay N round-trips back-to-back, which
        measured a 32% bind-rate LOSS at 4 shards.  One short-lived
        thread per additional shard: the spawn cost (~100us) is noise
        next to the millisecond-scale RPC it overlaps."""
        if len(calls) == 1 or not self._parallel:
            return [c() for c in calls]
        results: List[Any] = [None] * len(calls)
        errors: List[Optional[BaseException]] = [None] * len(calls)

        def run(i: int):
            try:
                results[i] = calls[i]()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors[i] = e

        threads = [threading.Thread(target=run, args=(i,), daemon=True,
                                    name="store-shard-fanout")
                   for i in range(1, len(calls))]
        for t in threads:
            t.start()
        run(0)  # the caller's thread takes shard 0's slice
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e
        return results

    def list_raw(self, prefix: str):
        outs = self._fan_out([
            (lambda s=s: s.list_raw(prefix)) for s in self._stores])
        entries: List[Tuple[str, int, Dict[str, Any]]] = []
        revs: List[int] = []
        for e, rev in outs:
            entries.extend(e)
            revs.append(rev)
        entries.sort(key=lambda kro: kro[0])  # the single store listed sorted
        return entries, format_rv(revs)

    def list(self, prefix: str):
        entries, rev = self.list_raw(prefix)
        scheme = self._stores[0]._scheme
        return [scheme.decode(obj) for _k, _r, obj in entries], rev

    def _scatter(self, positions_by_shard: Dict[int, List[int]],
                 call_for_shard: Callable[[int, List[int]], Callable],
                 out: List[Any]) -> List[Any]:
        shards = sorted(positions_by_shard)
        outs = self._fan_out([
            call_for_shard(si, positions_by_shard[si]) for si in shards])
        for si, res in zip(shards, outs):
            for p, r in zip(positions_by_shard[si], res):
                out[p] = r
        return out

    def get_raw_many(self, keys: List[str]) -> List[Optional[Dict[str, Any]]]:
        by_shard: Dict[int, List[int]] = {}
        for pos, key in enumerate(keys):
            by_shard.setdefault(self.map.shard_of_key(key), []).append(pos)
        return self._scatter(
            by_shard,
            lambda si, poss: (lambda: self._stores[si].get_raw_many(
                [keys[p] for p in poss])),
            [None] * len(keys))

    def commit_batch(self, ops: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        if any(op.get("op") == "delete" for op in ops):
            with self._stats_lock:
                self._delete_batches += 1
        by_shard: Dict[int, List[int]] = {}
        for pos, op in enumerate(ops):
            by_shard.setdefault(
                self.map.shard_of_key(op["key"]), []).append(pos)
        return self._scatter(
            by_shard,
            lambda si, poss: (lambda: self._stores[si].commit_batch(
                [ops[p] for p in poss])),
            [None] * len(ops))

    # -------------------------------------------------------------- watch

    def plan_resume(self, since_rev, current_rev_of: Callable[[int], int]):
        """-> (per_shard_since, position_seeds).  Encodes the resume
        semantics from the module docstring.  Position seeds for
        from-now shards are snapshotted BEFORE registration: an event
        committed between snapshot and attach is replayed on a bookmark
        resume instead of skipped — duplicates are idempotent upserts,
        gaps are lost state."""
        parsed = since_rev if isinstance(since_rev, tuple) else \
            parse_rv(since_rev)
        n = self.shards
        if isinstance(parsed, tuple):
            if len(parsed) != n:
                # a composite minted under a different shard count: the
                # only safe answer is the relist path
                raise TooOldResourceVersion(
                    f"composite resourceVersion arity {len(parsed)} does "
                    f"not match shard count {n}; relist required")
            # a part of 0 is SHARD 0's empty-at-list floor (its revisions
            # start at the 0 residue), not "from now": resume it with a
            # positive below-first-possible-rev value so everything
            # committed after the list replays — since_rev=0 there would
            # silently gap any event landing between the list and the
            # watch registration.  Shards i>0 have truthy floors (i) and
            # never hit this.
            return [p or 1 for p in parsed], list(parsed)
        r = int(parsed or 0)
        if r == 0:
            return [0] * n, [current_rev_of(i) for i in range(n)]
        if r < n:
            # below every possible committed revision: replay everything
            return [r] * n, [r] * n
        owner = r % n
        since, seeds = [], []
        for i in range(n):
            if i == owner:
                since.append(r)
                seeds.append(r)
            else:
                seeds.append(current_rev_of(i))
                since.append(0)
        return since, seeds

    def watch(self, prefix: str, since_rev=0,
              queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT) -> FanInWatcher:
        since, seeds = self.plan_resume(
            since_rev, lambda i: self._stores[i].current_revision())
        buffering = any(since)
        w = FanInWatcher(self, prefix, self.shards, queue_limit=queue_limit,
                         buffering=buffering)
        w.seed_positions(seeds)
        attached: List[Any] = []
        replays: List[list] = []
        try:
            for st, sr in zip(self._stores, since):
                if hasattr(st, "attach_watcher"):  # in-process shard
                    replays.append(st.attach_watcher(w, sr))
                    attached.append(st)
                else:  # remote shard: dedicated stream, forwarded by a pump
                    w.add_remote(st.watch(prefix, since_rev=sr,
                                          queue_limit=0))
        except Exception:
            for st in attached:
                st._remove_watcher(w)
            w.stop()
            raise
        for entries in replays:
            w._replay_entries(entries)
        if buffering:
            w._go_live()
        return w

    def _remove_watcher(self, w: Watcher):
        for st in self._stores:
            rm = getattr(st, "_remove_watcher", None)
            if rm is not None:
                rm(w)

    def _note_watch_eviction(self):
        with self._stats_lock:
            self._fanin_evictions += 1


def build_sharded_store(scheme_factory: Callable[[], Any], shards: int,
                        wal_path: Optional[str] = None,
                        wal_sync: str = "batch") -> ShardedStore:
    """N in-process shard Stores with stride revisions and per-shard WALs
    (``<wal_path>.shard<i>``).  Each shard gets its OWN scheme copy: the
    serialization caches stay per-shard feeds, exactly like the
    one-process-per-shard deployment."""
    stores = [
        Store(scheme_factory(),
              wal_path=f"{wal_path}.shard{i}" if wal_path else None,
              wal_sync=wal_sync, rev_offset=i, rev_stride=shards)
        for i in range(shards)
    ]
    return ShardedStore(stores)


class ShardedCacher:
    """Per-shard watch caches behind the Cacher read surface.

    Freshness is a PER-SHARD property: each shard cacher is sync-fed by
    its in-process shard (fresh by construction) or rides its own
    shard's progress-notify stream (RPC-free read-your-writes per
    shard).  Merged LISTs concatenate per-shard fresh snapshots and
    return a composite rv; merged watches fan into one queue
    (FanInWatcher) with bookmark support."""

    def __init__(self, store: ShardedStore, scheme,
                 queue_limit: int = DEFAULT_WATCH_QUEUE_LIMIT,
                 **cacher_kwargs):
        self._store = store
        self.map = store.map
        self._queue_limit = queue_limit
        self._shards = [
            Cacher(sub, scheme, queue_limit=queue_limit, **cacher_kwargs)
            for sub in store.shard_stores
        ]
        self._evict_lock = locksan.make_lock(
            "storage.ShardedCacher._evict_lock")
        self._fanin_evictions = 0

    @property
    def shard_cachers(self) -> List[Cacher]:
        return list(self._shards)

    def start(self) -> "ShardedCacher":
        for c in self._shards:
            c.start()
        return self

    def stop(self):
        for c in self._shards:
            c.stop()

    # ---------------------------------------------------------- aggregates

    @property
    def reseeds(self):
        return sum(c.reseeds for c in self._shards)

    @property
    def watch_evictions(self):
        with self._evict_lock:
            own = self._fanin_evictions
        return sum(c.watch_evictions for c in self._shards) + own

    @property
    def watch_wakeups(self):
        return sum(c.watch_wakeups for c in self._shards)

    @property
    def watch_events(self):
        return sum(c.watch_events for c in self._shards)

    @property
    def dispatch_indexed_hits(self):
        return sum(c.dispatch_indexed_hits for c in self._shards)

    @property
    def dispatch_scans(self):
        return sum(c.dispatch_scans for c in self._shards)

    # --------------------------------------------------------------- reads

    def get_raw(self, key: str):
        return self._shards[self.map.shard_of_key(key)].get_raw(key)

    def list_raw(self, prefix: str):
        # per-shard wait_fresh runs inside each cacher's list_raw;
        # against remote shards those freshness waits fan out
        # CONCURRENTLY (the store facade's rule — N back-to-back waits
        # would serialize the apiserver's LIST hot path), and in-process
        # shards stay serial on the one GIL
        outs = self._store._fan_out([
            (lambda c=c: c.list_raw(prefix)) for c in self._shards])
        entries: List[Tuple[str, int, Dict[str, Any]]] = []
        revs: List[int] = []
        for e, rev in outs:
            entries.extend(e)
            revs.append(rev)
        entries.sort(key=lambda kro: kro[0])
        return entries, format_rv(revs)

    def list_raw_indexed(self, prefix: str, field: str, value: str):
        """Merged indexed LIST: each shard cacher answers from its own
        secondary index (None from any shard = the index isn't declared —
        registration is module-level, so it's all-or-none across shards)
        and the merge is the list_raw merge over the narrowed sets."""
        outs = self._store._fan_out([
            (lambda c=c: c.list_raw_indexed(prefix, field, value))
            for c in self._shards])
        if any(o is None for o in outs):
            return None
        entries: List[Tuple[str, int, Dict[str, Any]]] = []
        revs: List[int] = []
        for e, rev in outs:
            entries.extend(e)
            revs.append(rev)
        entries.sort(key=lambda kro: kro[0])
        return entries, format_rv(revs)

    def compacted_revisions(self) -> List[int]:
        """Per-shard history floors, shard order (continue-token
        staleness: each composite part checks against its own shard)."""
        return [c.compacted_revisions()[0] for c in self._shards]

    def current_cached_revision(self) -> int:
        """Highest applied revision across the shard caches (the 1-shard
        facade's progress-bookmark source; multi-shard streams never ask
        — their position is the composite bookmark_rv)."""
        return max(c.current_cached_revision() for c in self._shards)

    # --------------------------------------------------------------- watch

    dispatch_index_capable = True

    def watch(self, prefix: str, since_rev=0,
              queue_limit: Optional[int] = None,
              index_hint=None) -> FanInWatcher:
        limit = self._queue_limit if queue_limit is None else queue_limit
        since, seeds = self._store.plan_resume(
            since_rev, lambda i: self._shards[i].current_cached_revision())
        n = len(self._shards)
        for c, sr in zip(self._shards, since):
            c.wait_fresh()
            if sr and sr >= n:
                # a REAL shard revision the client proved exists: wait
                # for this shard's cache to cover it before registering
                # (the Cacher.watch no-duplicates contract).  Parts below
                # n are empty-shard floor values — nothing to wait for.
                c._wait_rev_locked_entry(sr, c._fresh_timeout)
        w = FanInWatcher(self, prefix, n, queue_limit=limit,
                         buffering=any(since))
        w.seed_positions(seeds)
        attached: List[Cacher] = []
        replays: List[list] = []
        try:
            for c, sr in zip(self._shards, since):
                replays.append(c.attach_watcher(w, sr,
                                                index_hint=index_hint))
                attached.append(c)
        except Exception:
            for c in attached:
                c._remove_watcher(w)
            w.stop()
            raise
        for entries in replays:
            w._replay_entries(entries)
        if any(since):
            w._go_live()
        return w

    def _remove_watcher(self, w: Watcher):
        for c in self._shards:
            c._remove_watcher(w)

    def _note_watch_eviction(self):
        with self._evict_lock:
            self._fanin_evictions += 1
