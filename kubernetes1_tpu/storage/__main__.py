"""Standalone store entrypoint — the etcd process of the cluster.

    python -m kubernetes1_tpu.storage --socket /run/ktpu/store.sock \
        --wal /var/lib/ktpu/store.wal

N stateless apiservers point at it via --store-address; kill any apiserver
and the control plane keeps its state (the VERDICT r3 HA bar).
"""

import argparse
import signal
import threading

from ..machinery.scheme import global_scheme
from .server import StoreServer
from .store import Store


def main():
    ap = argparse.ArgumentParser(description="ktpu store server (etcd role)")
    ap.add_argument("--socket", default="",
                    help="unix socket path to serve on")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (used when --socket is not given)")
    ap.add_argument("--wal", default="", help="write-ahead log for durability")
    ap.add_argument("--tls-cert-file", default="")
    ap.add_argument("--tls-key-file", default="")
    ap.add_argument("--client-ca-file", default="",
                    help="require client certs signed by this CA (mTLS); "
                         "strongly recommended for TCP mode")
    args = ap.parse_args()
    if args.port and not args.socket and not args.client_ca_file:
        print("WARNING: TCP store without --client-ca-file accepts any "
              "client that can reach the port — use mTLS or a unix socket",
              flush=True)

    store = Store(global_scheme.copy(), wal_path=args.wal or None)
    address = args.socket if args.socket else (args.host, args.port)
    server = StoreServer(store, address,
                         tls_cert_file=args.tls_cert_file,
                         tls_key_file=args.tls_key_file,
                         client_ca_file=args.client_ca_file).start()
    shown = server.address if isinstance(server.address, str) \
        else f"{server.address[0]}:{server.address[1]}"
    print(f"ktpu-store serving on {shown}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
