"""Standalone store entrypoint — the etcd process of the cluster.

    python -m kubernetes1_tpu.storage --socket /run/ktpu/store.sock \
        --wal /var/lib/ktpu/store.wal

N stateless apiservers point at it via --store-address; kill any apiserver
and the control plane keeps its state (the VERDICT r3 HA bar).
"""

import argparse
import signal
import threading

from ..machinery.scheme import global_scheme
from .server import StoreServer
from .store import Store


def main():
    ap = argparse.ArgumentParser(description="ktpu store server (etcd role)")
    ap.add_argument("--socket", default="",
                    help="unix socket path to serve on")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (used when --socket is not given)")
    ap.add_argument("--wal", default="", help="write-ahead log for durability")
    ap.add_argument("--wal-sync", default="batch",
                    choices=("none", "batch", "always"),
                    help="WAL durability: one fsync per group commit "
                         "(batch, default), per record (always), or page-"
                         "cache only (none — loses the host-crash window)")
    ap.add_argument("--tls-cert-file", default="")
    ap.add_argument("--tls-key-file", default="")
    ap.add_argument("--client-ca-file", default="",
                    help="require client certs signed by this CA (mTLS); "
                         "strongly recommended for TCP mode")
    ap.add_argument("--standby-of", default="",
                    help="run as a warm standby replicating from this "
                         "primary store address; serves NotPrimary until "
                         "the primary dies, then self-promotes")
    ap.add_argument("--failover-grace", type=float, default=1.0,
                    help="seconds the primary must refuse connections "
                         "before the standby promotes itself")
    ap.add_argument("--primary-ca-file", default="",
                    help="CA to verify a TLS primary when replicating")
    ap.add_argument("--primary-cert-file", default="",
                    help="client cert for mTLS replication to the primary")
    ap.add_argument("--primary-key-file", default="")
    ap.add_argument("--repl-ack-policy", default="available",
                    choices=("available", "durable"),
                    help="replication ack gate on a timed-out standby: "
                         "'available' (default) acks unprotected and "
                         "counts it; 'durable' fails the write 503 until "
                         "a standby covers it — no ack ever outruns the "
                         "standby (applies to a standby after promotion)")
    ap.add_argument("--shard-index", type=int, default=0,
                    help="this store's shard index i of --shard-count N "
                         "(storage/shardmap.py): revisions are stamped "
                         "i + k*N so the shard set shares one globally-"
                         "unique, per-shard-strict revision space")
    ap.add_argument("--shard-count", type=int, default=1,
                    help="total shard count N (1 = unsharded, today's "
                         "revision numbering exactly)")
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="serve /metrics on this port (robustness "
                         "counters: WAL torn-tail repairs, standby "
                         "resyncs, unprotected acks); -1 disables, "
                         "0 picks a free port")
    args = ap.parse_args()
    if args.port and not args.socket and not args.client_ca_file:
        print("WARNING: TCP store without --client-ca-file accepts any "
              "client that can reach the port — use mTLS or a unix socket",
              flush=True)

    def serve_metrics(extra):
        """Optional /metrics for the store process (the apiserver exports
        the IN-PROCESS store's counters itself; a standalone store/standby
        needs its own port for the robustness counters)."""
        if args.metrics_port < 0:
            return None
        from ..utils.metrics import MetricsServer, Registry

        srv = MetricsServer(Registry(), port=args.metrics_port, extra=extra)
        srv.start()
        print(f"ktpu-store metrics on 127.0.0.1:{srv.port}/metrics",
              flush=True)
        return srv

    address = args.socket if args.socket else (args.host, args.port)
    if args.standby_of:
        from .remote import _parse_addresses
        from .standby import StandbyServer

        primary = _parse_addresses(args.standby_of)[0]
        standby = StandbyServer(primary, address,
                                wal_path=args.wal or None,
                                failover_grace=args.failover_grace,
                                tls_cert_file=args.tls_cert_file,
                                tls_key_file=args.tls_key_file,
                                client_ca_file=args.client_ca_file,
                                primary_ca_file=args.primary_ca_file,
                                primary_cert_file=args.primary_cert_file,
                                primary_key_file=args.primary_key_file,
                                repl_ack_policy=args.repl_ack_policy,
                                rev_offset=args.shard_index,
                                rev_stride=args.shard_count,
                                ).start()
        shown = standby.address if isinstance(standby.address, str) \
            else f"{standby.address[0]}:{standby.address[1]}"
        print(f"ktpu-store STANDBY serving on {shown} "
              f"(replicating from {args.standby_of})", flush=True)
        metrics = serve_metrics({
            "ktpu_standby_resyncs_total": lambda: standby.resyncs,
            "ktpu_standby_promoted": lambda: int(standby.promoted.is_set()),
            "ktpu_wal_torn_tail_repairs_total":
                lambda: standby.store.wal_torn_tail_repairs,
            "ktpu_store_unprotected_acks_total":
                lambda: standby.server.unprotected_acks,
        })
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        standby.stop()
        if metrics is not None:
            metrics.stop()
        return

    store = Store(global_scheme.copy(), wal_path=args.wal or None,
                  wal_sync=args.wal_sync,
                  rev_offset=args.shard_index, rev_stride=args.shard_count)
    server = StoreServer(store, address,
                         tls_cert_file=args.tls_cert_file,
                         tls_key_file=args.tls_key_file,
                         client_ca_file=args.client_ca_file,
                         repl_ack_policy=args.repl_ack_policy).start()
    shown = server.address if isinstance(server.address, str) \
        else f"{server.address[0]}:{server.address[1]}"
    print(f"ktpu-store serving on {shown}", flush=True)
    metrics = serve_metrics({
        "ktpu_wal_torn_tail_repairs_total":
            lambda: store.wal_torn_tail_repairs,
        "ktpu_store_unprotected_acks_total":
            lambda: server.unprotected_acks,
        "ktpu_store_commits_total": lambda: store.commit_count,
        # per-shard write-path economics (the bench's store_shards block
        # scrapes these off every shard process): group-commit occupancy
        # and the WAL fsync tail this shard actually pays
        "ktpu_store_commit_batches_total": lambda: store.commit_batches,
        "ktpu_store_batch_occupancy":
            lambda: (store.commit_count / store.commit_batches
                     if store.commit_batches else 0.0),
        # deletion-path economics (apiservers over a REMOTE store can't
        # render these — the counters live here, in the store process)
        "ktpu_store_delete_batch_ops_total":
            lambda: store.delete_batch_ops,
        "ktpu_store_delete_batches_total": lambda: store.delete_batches,
        "ktpu_store_delete_batch_occupancy":
            lambda: (store.delete_batch_ops / store.delete_batches
                     if store.delete_batches else 0.0),
        "ktpu_store_wal_fsync_p99_seconds":
            lambda: store.wal_fsync_seconds.quantile(0.99) or 0.0,
        "ktpu_store_shard_index": lambda: store.rev_offset,
    })
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()
    if metrics is not None:
        metrics.stop()


if __name__ == "__main__":
    main()
