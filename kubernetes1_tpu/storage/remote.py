"""RemoteStore: the apiserver's client to a StoreServer (the etcd3 client
role — staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go).

Implements the exact Store surface the registry consumes (create/get/list/
update_cas/guaranteed_update/delete/watch/current_revision/compact/close),
so a Master can be pointed at a store process instead of an in-process
Store and N such Masters serve one cluster.  guaranteed_update runs its
read-modify-CAS loop client-side, same as etcd3's txn retry (store.go:263).

Request/response calls use a small per-thread-free connection pool; every
watch gets its own dedicated streaming connection whose iterator mirrors
storage.store.Watcher (next_timeout semantics included) so the apiserver's
chunked-watch loop cannot tell the difference.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..machinery import Conflict, NotFound, WatchEvent
from ..machinery.codec import CodecError, get_codec
from ..machinery.scheme import Scheme
from . import wire
from .server import NotPrimary, error_from_wire
from ..client.retry import Backoff
from ..utils import faultline, locksan


def _parse_addresses(address) -> List[Union[str, Tuple[str, int]]]:
    """Accept a single address, a comma-separated string, or a list.
    Strings with ':' and no '/' are host:port; everything else is a unix
    socket path.  Multiple addresses = primary + standby(s): the client
    fails over on NotPrimary / connection refusal, mirroring the etcd
    client's multi-endpoint balancer."""
    if isinstance(address, (list, tuple)) and address and \
            not (len(address) == 2 and isinstance(address[1], int)):
        raw = list(address)
    elif isinstance(address, str):
        raw = [a.strip() for a in address.split(",") if a.strip()]
    else:
        raw = [address]
    out: List[Union[str, Tuple[str, int]]] = []
    for a in raw:
        if isinstance(a, str) and ":" in a and "/" not in a:
            host, _, port = a.rpartition(":")
            out.append((host, int(port)))
        elif isinstance(a, (list, tuple)):
            out.append((a[0], int(a[1])))
        else:
            out.append(a)
    return out


class RemoteWatcher:
    """Iterator over WatchEvents from a dedicated store connection;
    duck-types storage.store.Watcher (incl. next_timeout/
    next_batch_timeout/stop).

    Batch frames ({"events": [...]}) arrive as ONE queue wakeup; progress
    heartbeats ({"progress": {"rev": N}}) update `progress_rev` (the
    highest store revision the stream has proven fully delivered — the
    etcd progress-notify analog the remote cacher's freshness rides on)
    and wake `next_batch_timeout` with an EMPTY list so the consumer can
    advance freshness without waiting out its poll timeout."""

    def __init__(self, conn, f, framer=None, scheme: Optional[Scheme] = None,
                 fault_site: str = "store.watch", ts_sink=None):
        self._conn = conn
        self._f = f
        self._fault_site = fault_site
        # watch-lag SLI: event frames may carry the commit stamp of their
        # newest revision ("ts"/"ts_rev"); the sink (RemoteStore._note_
        # commit_ts) records it so this client can answer commit_ts_of
        self._ts_sink = ts_sink
        # binary fast path: a negotiated BinFramer replaces line reads;
        # event objects may arrive as codec bytes ("objraw") decoded
        # through the scheme's codec axis
        self._framer = framer
        self._scheme = scheme
        # items: a non-empty List[WatchEvent], a ("progress",) sentinel,
        # or None (EOF)
        self._q: "queue.Queue[Optional[list]]" = queue.Queue()
        self._buf: "deque[WatchEvent]" = deque()  # consumer thread only
        self._stopped = threading.Event()
        self.progress_rev = 0
        # closed=True means the stream is DEAD (store gone), not idle —
        # consumers must distinguish this from a heartbeat timeout or a
        # store restart would leave every watch silently stalled forever
        self.closed = False
        # push-mode delivery hook (set_notify, same contract as
        # storage.store.Watcher): fired after every queue transition so
        # the event-loop dispatcher can drain instead of parking a
        # thread.  Plain attribute, no lock: assignment is atomic, and
        # set_notify's immediate fire covers anything the pump put
        # before the hook landed.
        self._notify_fn: Optional[Callable[[], None]] = None
        t = threading.Thread(target=self._pump, daemon=True,
                             name="remote-store-watch")
        t.start()

    _PROGRESS = ["progress"]  # shared sentinel; identity-compared

    def _note_frame_ts(self, frame: dict) -> None:
        if self._ts_sink is None:
            return
        ts, ts_rev = frame.get("ts"), frame.get("ts_rev")
        if ts is None or not ts_rev:
            return
        try:
            self._ts_sink(int(ts_rev), float(ts))
        except (TypeError, ValueError):
            pass  # malformed stamp: lag is best-effort, never fatal

    def _event(self, e: dict) -> WatchEvent:
        raw = e.get("objraw")
        if raw is not None:
            return WatchEvent(
                e["type"],
                self._scheme.decode_bytes(raw, self._framer.codec_id))
        return WatchEvent(e["type"], e["object"])

    def _recv_frame(self) -> Optional[dict]:
        """One wire frame (None = legacy heartbeat).  Raises on stream
        end: BrokenPipeError/FrameTruncated/CodecError all land in the
        pump's except and close the stream cleanly — a torn length-
        prefixed frame is a dead stream, never a hang."""
        if self._framer is not None:
            return self._framer.recv()
        line = self._f.readline()
        if not line:
            raise BrokenPipeError("watch stream closed")
        line = line.strip()
        if not line:
            return None  # legacy heartbeat
        return json.loads(line)

    def _wake(self):
        fn = self._notify_fn
        if fn is not None:
            fn()  # non-blocking by contract (see set_notify)

    def _pump(self):
        try:
            while True:
                # fault injection: an injected drop here kills the stream
                # like a mid-frame cut — `closed` is set below and the
                # cacher reseeds (list + fresh watch), losing nothing
                faultline.check(self._fault_site)
                frame = self._recv_frame()
                if frame is None:
                    continue  # legacy heartbeat
                ev = frame.get("event")
                if ev is not None:
                    self._note_frame_ts(frame)
                    self._q.put([self._event(ev)])
                    self._wake()
                    continue
                evs = frame.get("events")
                if evs is not None:
                    self._note_frame_ts(frame)
                    self._q.put([self._event(e) for e in evs])
                    self._wake()
                    continue
                prog = frame.get("progress")
                if prog is not None:
                    rev = int(prog.get("rev") or 0)
                    if rev > self.progress_rev:
                        self.progress_rev = rev
                    self._q.put(self._PROGRESS)
                    self._wake()
        except (OSError, ValueError):
            pass
        finally:
            self.closed = True
            self._q.put(None)  # EOF sentinel: the stream is dead
            self._wake()

    def stop(self):
        self._stopped.set()
        self.closed = True
        try:
            self._conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._conn.close()
        except OSError:
            pass
        self._q.put(None)
        self._wake()

    def __iter__(self):
        return self

    def __next__(self) -> WatchEvent:
        while True:
            if self._buf:
                return self._buf.popleft()
            item = self._q.get()
            if item is None or self._stopped.is_set():
                raise StopIteration
            if item is self._PROGRESS:
                continue
            self._buf.extend(item)

    def next_timeout(self, timeout: float) -> Optional[WatchEvent]:
        deadline = time.monotonic() + timeout
        while True:
            if self._buf:
                return self._buf.popleft()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                return None
            if item is None:
                self._stopped.set()
                return None
            if item is self._PROGRESS:
                continue  # progress_rev already updated by the pump
            self._buf.extend(item)

    def next_batch_timeout(self, timeout: float) -> Optional[list]:
        """One batch of events, [] on a progress-only wakeup, None on
        timeout/stream-end."""
        if not self._buf:
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                return None
            if item is None:
                self._stopped.set()
                return None
            if item is self._PROGRESS:
                return []
            self._buf.extend(item)
        # drain whatever else already arrived — one apply per wakeup
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)
                break
            if nxt is self._PROGRESS:
                continue
            self._buf.extend(nxt)
        out = list(self._buf)
        self._buf.clear()
        return out

    def set_notify(self, fn: Optional[Callable[[], None]]):
        """Install a delivery hook for PUSH-mode consumers (the
        event-loop watch dispatcher) — same contract as
        storage.store.Watcher.set_notify: called after every queue
        transition, must never block, fires once on install so anything
        already queued is observed."""
        self._notify_fn = fn
        if fn is not None:
            fn()

    def next_batch_nowait(self) -> Optional[list]:
        """Non-blocking twin of next_batch_timeout (the cacher
        batch-cursor contract the dispatcher drains on notify):
        everything deliverable right now as one list, ``[]`` when
        nothing is queued or the wakeup was progress-only, ``None`` on
        stream end.  Consumer-thread only, like the blocking variant."""
        if not self._buf:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return []
            if item is None:
                self._stopped.set()
                return None
            if item is not self._PROGRESS:
                self._buf.extend(item)
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)  # keep the EOF sentinel for next call
                break
            if nxt is self._PROGRESS:
                continue
            self._buf.extend(nxt)
        out = list(self._buf)
        self._buf.clear()
        return out


class RemoteStore:
    def __init__(self, scheme: Scheme,
                 address: Union[str, Tuple[str, int]],
                 ca_file: str = "", cert_file: str = "", key_file: str = "",
                 timeout: float = 30.0, codec: str = "json",
                 site_prefix: str = "store"):
        self._scheme = scheme
        # faultline site family for this link: the default client speaks
        # on store.rpc/store.watch; a SHARD link (storage/shardmap.py)
        # passes site_prefix="store.shard" so chaos schedules can fault
        # shard traffic independently of an unsharded store's
        self._site_rpc = f"{site_prefix}.rpc"
        self._site_watch = f"{site_prefix}.watch"
        self._addrs = _parse_addresses(address)
        self._active = 0
        self.timeout = timeout
        # wire codec: "json" = the legacy newline-JSON protocol with zero
        # negotiation; anything else is negotiated per dial and falls
        # back to newline-JSON when the server declines (old server,
        # standby) — see storage/wire.py.  Validated here so a typo'd
        # --wire-codec fails at construction, not mid-traffic.
        if codec != "json":
            get_codec(codec)
        self.codec = codec
        self._ssl_ctx = None
        if ca_file:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl_ctx.load_verify_locations(cafile=ca_file)
            if cert_file:
                # mTLS: the store requires a cluster-CA client cert
                self._ssl_ctx.load_cert_chain(certfile=cert_file,
                                              keyfile=key_file or None)
        self._pool: List = []
        self._lock = locksan.make_lock("RemoteStore._lock")
        self._next_id = 0
        # highest store revision observed in any response from this
        # client: the remote cacher's RPC-free freshness target (a write
        # through this client is read-your-writes; see Cacher.wait_fresh)
        self._seen_rev = 0
        # watch-lag SLI: commit stamps carried on watch frames (one per
        # frame, keyed by the frame's newest revision) — bounded; the
        # serving layer only ever asks about just-delivered revisions
        self._commit_ts: Dict[int, float] = {}
        self._commit_ts_order: deque = deque()

    def _note_rev(self, rev) -> None:
        try:
            rev = int(rev)
        except (TypeError, ValueError):
            return
        with self._lock:
            if rev > self._seen_rev:
                self._seen_rev = rev

    def _note_obj_rev(self, encoded: Optional[dict]) -> Optional[dict]:
        if encoded:
            self._note_rev((encoded.get("metadata") or {})
                           .get("resourceVersion"))
        return encoded

    def last_seen_revision(self) -> int:
        with self._lock:
            return self._seen_rev

    def _note_commit_ts(self, rev: int, ts: float) -> None:
        with self._lock:
            self._commit_ts[rev] = ts
            self._commit_ts_order.append(rev)
            while len(self._commit_ts_order) > 2048:
                self._commit_ts.pop(self._commit_ts_order.popleft(), None)

    def commit_ts_of(self, rev: int) -> Optional[float]:
        """Monotonic commit stamp for a revision this client saw a watch
        frame for (None otherwise — frame-granular, unlike the in-process
        store's per-revision ring).  Comparable across processes on one
        host: CLOCK_MONOTONIC is system-wide on Linux."""
        with self._lock:
            return self._commit_ts.get(rev)

    @property
    def address(self):
        """The currently-active server (first one at construction)."""
        return self._addrs[self._active]

    # ------------------------------------------------------------- transport

    def _advance(self, failed_addr):
        """Fail over to the next server.  Guarded so N threads observing
        the same dead primary advance ONCE, and the pool (connections to
        the failed server) is dropped with it."""
        with self._lock:
            if self._addrs[self._active] != failed_addr \
                    or len(self._addrs) < 2:
                return
            self._active = (self._active + 1) % len(self._addrs)
            pool, self._pool = self._pool, []
        for conn, _f, _framer in pool:
            try:
                conn.close()
            except OSError:
                pass

    def _connect(self, timeout: Optional[float], addr=None):
        addr = addr if addr is not None else self.address
        if isinstance(addr, str):
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(timeout)
            conn.connect(addr)
        else:
            conn = socket.create_connection(tuple(addr), timeout=timeout)
        if self._ssl_ctx is not None:
            host = addr if isinstance(addr, str) else addr[0]
            conn = self._ssl_ctx.wrap_socket(conn, server_hostname=host)
        return conn, conn.makefile("rwb")

    def _connect_negotiated(self, timeout: Optional[float], addr=None):
        """Dial and (when a non-JSON codec is configured) negotiate the
        binary framing for this connection.  Returns (conn, f, framer)
        with framer=None meaning legacy newline-JSON — the fallback when
        the server declines.  Transport failures during negotiation raise
        OSError with NOTHING application-visible sent, so callers treat
        them exactly like dial failures (always safe to fail over)."""
        conn, f = self._connect(timeout, addr)
        if self.codec == "json":
            return conn, f, None
        try:
            f.write(json.dumps(wire.negotiate_request(self.codec))
                    .encode() + b"\n")
            f.flush()
            line = f.readline()
            if not line:
                raise BrokenPipeError("store closed during negotiation")
            resp = json.loads(line)
        except ValueError as e:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(
                f"store: corrupt negotiation response: {e}") from e
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            raise
        if wire.negotiation_accepted(resp, self.codec):
            return conn, f, wire.BinFramer(f, self.codec,
                                           site=self._site_rpc)
        # old server / unsupported codec: the connection stays usable on
        # the legacy protocol — negotiation is an upgrade, not a gate
        return conn, f, None

    _IDEMPOTENT = frozenset({"get", "list", "current_revision", "compact"})

    def _call(self, method: str, params: Optional[dict] = None):
        # Retry/failover rules (same safety contract as the REST client's
        # stale-keep-alive retry, extended across servers):
        #  - a pooled connection may be stale (store restarted): retry on a
        #    FRESH connection only when the store cannot have seen the
        #    request (failure while SENDING) or the method is idempotent —
        #    a fully-sent create/delete/update_cas may have been APPLIED,
        #    and re-sending would fabricate AlreadyExists/NotFound/Conflict
        #  - a NotPrimary answer means the request was definitely NOT
        #    applied: always safe to fail over to the next server
        #  - a fresh-dial refusal means this server is down: fail over
        #    (nothing was sent)
        last_exc: Optional[Exception] = None
        # Multi-server: enough attempts (with a small sleep once every
        # server has been tried) to ride out a standby's failover grace
        # window (~1s) — during it the old primary refuses and the standby
        # still answers NotPrimary, and a client that gave up instantly
        # would surface a spurious 500 for a blip the system is designed
        # to absorb.  Single-server: failover is impossible, so keep the
        # old fast-fail (one pooled try + one fresh redial, no sleeps).
        attempts = 2 if len(self._addrs) == 1 else 2 + 6 * len(self._addrs)
        # floor keeps the per-attempt pause from jittering below what the
        # grace-window ride-out needs; the cap bounds tail latency
        backoff = Backoff(base=0.25, factor=1.5, cap=0.4)
        for attempt in range(attempts):
            if attempt > len(self._addrs):
                backoff.sleep(floor=0.1)
            with self._lock:
                # retries dial FRESH: after a store restart the whole pool
                # is stale, and popping another dead pair would burn the
                # attempt without ever reaching a live server
                pair = (self._pool.pop()
                        if self._pool and attempt == 0 else None)
                self._next_id += 1
                rid = self._next_id
                addr = self._addrs[self._active]
            pooled = pair is not None
            if pair is None:
                try:
                    pair = self._connect_negotiated(self.timeout, addr)
                except OSError as e:
                    last_exc = ConnectionError(
                        f"store {addr} unreachable: {e}")
                    self._advance(addr)
                    continue
            conn, f, framer = pair
            sent = False
            resp = None
            try:
                # fault injection BEFORE the send: `sent` stays False, so
                # the existing may-have-been-applied retry rules stay
                # exactly as safe under chaos as under real dial failures
                faultline.check(self._site_rpc)
                req = {"id": rid, "method": method, "params": params or {}}
                if framer is not None:
                    # a send that dies mid-frame leaves an INCOMPLETE
                    # length-prefixed frame the server can never dispatch,
                    # but `sent` still goes True only after a full send —
                    # the conservative rule costs nothing and keeps the
                    # two framings under one contract
                    framer.send(req)
                    sent = True
                    resp = framer.recv()
                else:
                    f.write(json.dumps(req).encode() + b"\n")
                    f.flush()
                    sent = True
                    line = f.readline()
                    if not line:
                        raise BrokenPipeError("store closed the connection")
            except CodecError:
                try:
                    conn.close()
                except OSError:
                    pass
                raise ConnectionError("store: corrupt response frame")
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                try:
                    conn.close()
                except OSError:
                    pass
                last_exc = ConnectionError(f"store {addr}: {e}")
                if sent and method not in self._IDEMPOTENT:
                    # may have been applied over there — nowhere is it safe
                    # to re-send (the standby shares the replicated state)
                    raise last_exc
                if not pooled:
                    self._advance(addr)  # fresh connection failed: move on
                continue
            if resp is None:
                try:
                    resp = json.loads(line)
                except ValueError:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    raise ConnectionError("store: corrupt response frame")
            if resp.get("id") != rid:
                try:
                    conn.close()
                except OSError:
                    pass
                raise ConnectionError("store: response id mismatch")
            if resp.get("error"):
                err = error_from_wire(resp["error"])
                if isinstance(err, NotPrimary):
                    # standby answered: request NOT applied; try the next
                    # server (it may have just been promoted)
                    try:
                        conn.close()
                    except OSError:
                        pass
                    last_exc = err
                    self._advance(addr)
                    continue
                with self._lock:
                    self._pool.append(pair)
                raise err
            with self._lock:
                self._pool.append(pair)
            return resp.get("result")
        raise last_exc if last_exc else ConnectionError(
            f"store unreachable on every address: {self._addrs}")

    # ------------------------------------------------------------ operations

    def create(self, key: str, obj) -> Any:
        return self._scheme.decode(self._note_obj_rev(
            self._call("create", {"key": key,
                                  "obj": self._scheme.encode(obj)})))

    def get(self, key: str) -> Any:
        return self._scheme.decode(self._note_obj_rev(
            self._call("get", {"key": key})))

    def get_or_none(self, key: str):
        try:
            return self.get(key)
        except NotFound:
            return None

    def get_raw_many(self, keys: List[str]) -> List[Optional[dict]]:
        """Encoded wire dicts for N keys (None where absent) in ONE RPC —
        the read half of a bulk read-modify-CAS (registry.bind_batch)."""
        items = self._call("get_many", {"keys": keys})["items"]
        for it in items:
            self._note_obj_rev(it)
        return items

    def list(self, prefix: str) -> Tuple[List[Any], int]:
        res = self._call("list", {"prefix": prefix})
        self._note_rev(res["rev"])
        return [self._scheme.decode(o) for o in res["items"]], res["rev"]

    def list_raw(self, prefix: str) -> Tuple[List[Tuple[str, int, dict]], int]:
        """(key, rev, encoded obj) entries — the watch cache's seed path.
        The store ships its committed wire form with keys verbatim."""
        res = self._call("list_raw", {"prefix": prefix})
        self._note_rev(res["rev"])
        return [(k, r, o) for k, r, o in res["items"]], res["rev"]

    def update_cas(self, key: str, obj) -> Any:
        return self._scheme.decode(self._note_obj_rev(
            self._call("update_cas", {"key": key,
                                      "obj": self._scheme.encode(obj)})))

    def commit_batch(self, ops: List[dict]) -> List[dict]:
        """N mutations in one RPC and one store group commit.  Same
        contract as Store.commit_batch: encoded dicts in, per-op
        {"obj": encoded} or {"error": ApiError instance} out."""
        res = self._call("commit_batch", {"ops": ops})
        out = []
        for r in res["results"]:
            err = r.get("error")
            if err is not None:
                out.append({"error": error_from_wire(err)})
            else:
                out.append({"obj": self._note_obj_rev(r["obj"])})
        return out

    def guaranteed_update(self, key: str,
                          update_fn: Callable[[Any], Any]) -> Any:
        while True:
            cur = self.get(key)
            updated = update_fn(cur)
            if updated is None:
                updated = cur
            try:
                return self.update_cas(key, updated)
            except Conflict:
                continue

    def delete(self, key: str, expect_rv: str = "") -> Any:
        return self._scheme.decode(self._note_obj_rev(
            self._call("delete", {"key": key, "expect_rv": expect_rv})))

    def current_revision(self) -> int:
        rev = int(self._call("current_revision"))
        self._note_rev(rev)
        return rev

    def compact(self, keep_last: int = 1000):
        self._call("compact", {"keep_last": keep_last})

    # ------------------------------------------------------------------ watch

    def watch(self, prefix: str, since_rev: int = 0,
              queue_limit: Optional[int] = None) -> RemoteWatcher:
        """queue_limit rides the RPC so the server-side Watcher honors it
        (0 = unbounded — the cacher's own feed must never be evicted by
        the bound meant for slow CLIENTS; None = the server default)."""
        last_exc: Optional[Exception] = None
        attempts = 2 if len(self._addrs) == 1 else 2 + 6 * len(self._addrs)
        backoff = Backoff(base=0.25, factor=1.5, cap=0.4)
        for attempt in range(attempts):
            if attempt > len(self._addrs):
                backoff.sleep(floor=0.1)  # ride out a failover grace window
            addr = self._addrs[self._active]
            try:
                faultline.check(self._site_watch)  # injected dial refusal
                conn, f, framer = self._connect_negotiated(
                    self.timeout, addr)
            except OSError as e:
                last_exc = ConnectionError(f"store {addr} unreachable: {e}")
                self._advance(addr)
                continue
            params = {"prefix": prefix, "since_rev": since_rev}
            if queue_limit is not None:
                params["queue_limit"] = queue_limit
            try:
                req = {"id": 0, "method": "watch", "params": params}
                if framer is not None:
                    framer.send(req)
                    resp = framer.recv()
                else:
                    f.write(json.dumps(req).encode() + b"\n")
                    f.flush()
                    line = f.readline()
                    if not line:
                        raise ConnectionError(f"store {addr} closed")
                    resp = json.loads(line)
                if resp.get("error"):
                    err = error_from_wire(resp["error"])
                    if isinstance(err, NotPrimary):
                        conn.close()
                        last_exc = err
                        self._advance(addr)
                        continue
                    conn.close()
                    raise err  # e.g. TooOldResourceVersion: a real answer
            except (ConnectionError, OSError, ValueError) as e:
                conn.close()
                last_exc = e
                self._advance(addr)
                continue
            except BaseException:
                conn.close()
                raise
            conn.settimeout(None)  # the stream blocks until events arrive
            if framer is not None:
                framer.site = self._site_watch  # stream faults tear frames
            return RemoteWatcher(conn, f, framer=framer,
                                 scheme=self._scheme,
                                 fault_site=self._site_watch,
                                 ts_sink=self._note_commit_ts)
        raise last_exc if last_exc else ConnectionError(
            f"store watch failed on every address: {self._addrs}")

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for conn, _f, _framer in pool:
            try:
                conn.close()
            except OSError:
                pass
