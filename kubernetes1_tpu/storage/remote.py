"""RemoteStore: the apiserver's client to a StoreServer (the etcd3 client
role — staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go).

Implements the exact Store surface the registry consumes (create/get/list/
update_cas/guaranteed_update/delete/watch/current_revision/compact/close),
so a Master can be pointed at a store process instead of an in-process
Store and N such Masters serve one cluster.  guaranteed_update runs its
read-modify-CAS loop client-side, same as etcd3's txn retry (store.go:263).

Request/response calls use a small per-thread-free connection pool; every
watch gets its own dedicated streaming connection whose iterator mirrors
storage.store.Watcher (next_timeout semantics included) so the apiserver's
chunked-watch loop cannot tell the difference.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..machinery import Conflict, NotFound, WatchEvent
from ..machinery.scheme import Scheme
from .server import error_from_wire


class RemoteWatcher:
    """Iterator over WatchEvents from a dedicated store connection;
    duck-types storage.store.Watcher (incl. next_timeout/stop)."""

    def __init__(self, conn, f):
        self._conn = conn
        self._f = f
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = threading.Event()
        # closed=True means the stream is DEAD (store gone), not idle —
        # consumers must distinguish this from a heartbeat timeout or a
        # store restart would leave every watch silently stalled forever
        self.closed = False
        t = threading.Thread(target=self._pump, daemon=True,
                             name="remote-store-watch")
        t.start()

    def _pump(self):
        try:
            for line in self._f:
                line = line.strip()
                if not line:
                    continue  # heartbeat
                frame = json.loads(line)
                ev = frame.get("event")
                if ev is None:
                    continue
                self._q.put(WatchEvent(ev["type"], ev["object"]))
        except (OSError, ValueError):
            pass
        finally:
            self.closed = True
            self._q.put(None)  # EOF sentinel: the stream is dead

    def stop(self):
        self._stopped.set()
        self.closed = True
        try:
            self._conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._conn.close()
        except OSError:
            pass
        self._q.put(None)

    def __iter__(self):
        return self

    def __next__(self) -> WatchEvent:
        ev = self._q.get()
        if ev is None or self._stopped.is_set():
            raise StopIteration
        return ev

    def next_timeout(self, timeout: float) -> Optional[WatchEvent]:
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is None:
            self._stopped.set()
            return None
        return ev


class RemoteStore:
    def __init__(self, scheme: Scheme,
                 address: Union[str, Tuple[str, int]],
                 ca_file: str = "", cert_file: str = "", key_file: str = "",
                 timeout: float = 30.0):
        self._scheme = scheme
        self.address = address
        self.timeout = timeout
        self._ssl_ctx = None
        if ca_file:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl_ctx.load_verify_locations(cafile=ca_file)
            if cert_file:
                # mTLS: the store requires a cluster-CA client cert
                self._ssl_ctx.load_cert_chain(certfile=cert_file,
                                              keyfile=key_file or None)
        self._pool: List = []
        self._lock = threading.Lock()
        self._next_id = 0

    # ------------------------------------------------------------- transport

    def _connect(self, timeout: Optional[float]):
        if isinstance(self.address, str):
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(timeout)
            conn.connect(self.address)
        else:
            conn = socket.create_connection(tuple(self.address),
                                            timeout=timeout)
        if self._ssl_ctx is not None:
            host = self.address if isinstance(self.address, str) \
                else self.address[0]
            conn = self._ssl_ctx.wrap_socket(conn, server_hostname=host)
        return conn, conn.makefile("rwb")

    _IDEMPOTENT = frozenset({"get", "list", "current_revision", "compact"})

    def _call(self, method: str, params: Optional[dict] = None):
        # A pooled connection may be stale (store restarted); one retry on
        # a FRESH connection is safe only when the store cannot have seen
        # the request (failure while SENDING) or the method is idempotent —
        # a fully-sent create/delete/update_cas may have been APPLIED, and
        # re-sending it would fabricate AlreadyExists/NotFound/Conflict
        # errors (same rule as the REST client's stale-keep-alive retry).
        for attempt in (0, 1):
            with self._lock:
                # the retry attempt dials FRESH: after a store restart the
                # whole pool is stale, and popping another dead pair would
                # burn the one retry without ever reaching the live server
                pair = (self._pool.pop()
                        if self._pool and attempt == 0 else None)
                self._next_id += 1
                rid = self._next_id
            pooled = pair is not None
            if pair is None:
                pair = self._connect(self.timeout)
            conn, f = pair
            sent = False
            retriable = lambda: (pooled and attempt == 0  # noqa: E731
                                 and (not sent or method in self._IDEMPOTENT))
            try:
                f.write(json.dumps({"id": rid, "method": method,
                                    "params": params or {}}).encode() + b"\n")
                f.flush()
                sent = True
                line = f.readline()
            except (BrokenPipeError, ConnectionResetError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                if retriable():
                    continue
                raise ConnectionError(f"store {self.address} unreachable")
            if not line:
                try:
                    conn.close()
                except OSError:
                    pass
                if retriable():
                    continue
                raise ConnectionError(f"store {self.address} closed")
            break
        try:
            resp = json.loads(line)
        except ValueError:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError("store: corrupt response frame")
        if resp.get("id") != rid:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError("store: response id mismatch")
        with self._lock:
            self._pool.append(pair)
        if resp.get("error"):
            raise error_from_wire(resp["error"])
        return resp.get("result")

    # ------------------------------------------------------------ operations

    def create(self, key: str, obj) -> Any:
        return self._scheme.decode(
            self._call("create", {"key": key,
                                  "obj": self._scheme.encode(obj)}))

    def get(self, key: str) -> Any:
        return self._scheme.decode(self._call("get", {"key": key}))

    def get_or_none(self, key: str):
        try:
            return self.get(key)
        except NotFound:
            return None

    def list(self, prefix: str) -> Tuple[List[Any], int]:
        res = self._call("list", {"prefix": prefix})
        return [self._scheme.decode(o) for o in res["items"]], res["rev"]

    def update_cas(self, key: str, obj) -> Any:
        return self._scheme.decode(
            self._call("update_cas", {"key": key,
                                      "obj": self._scheme.encode(obj)}))

    def guaranteed_update(self, key: str,
                          update_fn: Callable[[Any], Any]) -> Any:
        while True:
            cur = self.get(key)
            updated = update_fn(cur)
            if updated is None:
                updated = cur
            try:
                return self.update_cas(key, updated)
            except Conflict:
                continue

    def delete(self, key: str, expect_rv: str = "") -> Any:
        return self._scheme.decode(
            self._call("delete", {"key": key, "expect_rv": expect_rv}))

    def current_revision(self) -> int:
        return int(self._call("current_revision"))

    def compact(self, keep_last: int = 1000):
        self._call("compact", {"keep_last": keep_last})

    # ------------------------------------------------------------------ watch

    def watch(self, prefix: str, since_rev: int = 0) -> RemoteWatcher:
        conn, f = self._connect(self.timeout)
        try:
            f.write(json.dumps({"id": 0, "method": "watch",
                                "params": {"prefix": prefix,
                                           "since_rev": since_rev}})
                    .encode() + b"\n")
            f.flush()
            line = f.readline()
            if not line:
                raise ConnectionError(f"store {self.address} closed")
            resp = json.loads(line)
            if resp.get("error"):
                raise error_from_wire(resp["error"])
        except BaseException:
            conn.close()
            raise
        conn.settimeout(None)  # the stream blocks until events arrive
        return RemoteWatcher(conn, f)

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for conn, _f in pool:
            try:
                conn.close()
            except OSError:
                pass
