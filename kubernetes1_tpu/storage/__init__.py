from .store import (
    DEFAULT_WATCH_QUEUE_LIMIT,
    ReplicaFeed,
    StopUpdate,
    Store,
    Watcher,
)
from .cacher import CacheNotReady, Cacher
from .shardmap import (
    FanInWatcher,
    ShardMap,
    ShardedCacher,
    ShardedStore,
    build_sharded_store,
    format_rv,
    parse_rv,
    parse_shard_addresses,
)
