from .store import (
    DEFAULT_WATCH_QUEUE_LIMIT,
    ReplicaFeed,
    StopUpdate,
    Store,
    Watcher,
)
from .cacher import CacheNotReady, Cacher
