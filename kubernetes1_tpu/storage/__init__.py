from .store import ReplicaFeed, StopUpdate, Store, Watcher
