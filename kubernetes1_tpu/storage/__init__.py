from .store import Store, Watcher, StopUpdate
