// ktpu-cri-runtime — native container runtime behind the CRI socket.
//
// C++ implementation of the kubelet's RuntimeService protocol
// (kubelet/cri.py; ref: pkg/kubelet/apis/cri/v1alpha1/runtime/api.proto +
// dockershim as the server role): newline-delimited JSON frames over a
// unix socket. Containers are host processes — fork/exec with the
// ContainerSpec's env (TPU_* injection included), own process group,
// per-container log files, cgroup joining via the cgroup_procs_files the
// kubelet computes, cpuset pinning via sched_setaffinity — the same
// contract as the Python ProcessRuntime, with no Python runtime needed on
// the node. A kubelet pointed at this socket via RemoteRuntime drives it
// unchanged:
//
//   ktpu-cri-runtime --socket /run/ktpu/cri.sock --root /var/lib/ktpu
//   Kubelet(cs, node, runtime=RemoteRuntime("/run/ktpu/cri.sock"))
//
// Build: make -C kubernetes1_tpu/native

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <grp.h>
#include <sched.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <map>
#include <set>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "json.hpp"

using ktpu::Json;
using ktpu::JsonArray;
using ktpu::JsonObject;

namespace {

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec + ts.tv_nsec / 1e9;
}

void mkdirs(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i < path.size(); ++i) {
    cur += path[i];
    if ((path[i] == '/' && i > 0) || i + 1 == path.size())
      mkdir(cur.c_str(), 0755);
  }
}

bool probe_mount_ns() {
  // can this host enter a private mount namespace? (mirrors the Python
  // runtime's _probe_mount_ns; without it, mounts degrade to env-only)
  int rc = system(
      "unshare --mount --propagation private -- sh -c 'exit 0' "
      ">/dev/null 2>&1");
  return rc == 0;
}

std::string sh_quote(const std::string& s) {
  std::string out = "'";
  for (char c : s) {
    if (c == '\'') out += "'\\''";
    else out += c;
  }
  out += "'";
  return out;
}

std::string gen_id(const char* prefix) {
  static std::atomic<uint64_t> counter{0};
  char buf[64];
  snprintf(buf, sizeof buf, "%s-%lx-%llx", prefix, (unsigned long)getpid(),
           (unsigned long long)++counter);
  return buf;
}

struct Sandbox {
  std::string id, pod_name, pod_namespace, pod_uid;
  std::string state = "SANDBOX_READY";
  double created_at = 0;
  JsonObject labels;
};

struct Container {
  std::string id, sandbox_id, name, image;
  std::string state = "CREATED";  // CREATED | RUNNING | EXITED
  bool has_exit = false;
  int exit_code = 0;
  double started_at = 0, finished_at = 0;
  int restart_count = 0;
  std::string log_path;
  // config
  std::vector<std::string> argv;
  JsonObject env;
  std::string working_dir;
  std::vector<std::string> cgroup_procs_files;
  std::vector<int> cpuset;
  JsonArray mounts;  // [{name, host_path, container_path, read_only}]
  // securityContext (ref pkg/securitycontext): drop to this uid/gid in
  // the child before exec; -1 = inherit the runtime's user
  long run_as_user = -1, run_as_group = -1;
  pid_t pid = -1;
  // previous cpu sample for rate computation (cadvisor's method)
  double cpu_ticks_prev = -1;
  double cpu_sample_ts = 0;
};

class Runtime {
 public:
  explicit Runtime(const std::string& root)
      : root_(root), mount_ns_(probe_mount_ns()) {
    mkdirs(root_);
    mkdirs(root_ + "/logs");
  }

  Json dispatch(const std::string& method, const Json& p) {
    if (method == "capabilities") {
      JsonObject o;
      o["real_pids"] = Json(true);
      o["root"] = Json(root_);
      // identity a no-runAsUser container execs as: the kubelet's
      // runAsNonRoot verification checks THIS, not its own euid
      o["default_uid"] = Json((int64_t)geteuid());
      return Json(o);
    }
    if (method == "version") return Json(std::string("ktpu-cri-runtime/0.1"));
    if (method == "run_pod_sandbox") return run_pod_sandbox(p);
    if (method == "stop_pod_sandbox") return stop_pod_sandbox(p);
    if (method == "remove_pod_sandbox") return remove_pod_sandbox(p);
    if (method == "list_pod_sandboxes") return list_pod_sandboxes();
    if (method == "create_container") return create_container(p);
    if (method == "start_container") return start_container(p);
    if (method == "stop_container") return stop_container(p);
    if (method == "remove_container") return remove_container(p);
    if (method == "list_containers") return list_containers();
    if (method == "container_status") return container_status(p);
    if (method == "read_log") return read_log(p);
    if (method == "container_stats") return container_stats(p);
    if (method == "exec_in_container") return exec_in_container(p);
    if (method == "exec_capture") return exec_capture(p);
    if (method == "set_container_affinity") return set_affinity(p);
    if (method == "pull_image") {
      std::lock_guard<std::mutex> l(mu_);
      images_.insert(p.get("image"));
      return Json(p.get("image"));
    }
    if (method == "list_images") {
      std::lock_guard<std::mutex> l(mu_);
      JsonArray out;
      for (const auto& img : images_) out.push_back(Json(img));
      return Json(out);
    }
    if (method == "image_present") {
      std::lock_guard<std::mutex> l(mu_);
      return Json(images_.count(p.get("image")) > 0);
    }
    throw std::runtime_error("unknown CRI method '" + method + "'");
  }

 private:
  std::string root_;
  bool mount_ns_;
  std::mutex mu_;
  std::map<std::string, Sandbox> sandboxes_;
  std::map<std::string, Container> containers_;
  std::set<std::string> images_;  // advisory image inventory (ImageService)

  // ------------------------------------------------------------ sandboxes

  Json run_pod_sandbox(const Json& p) {
    Sandbox sb;
    sb.id = gen_id("sb");
    sb.pod_name = p.get("pod_name");
    sb.pod_namespace = p.get("pod_namespace");
    sb.pod_uid = p.get("pod_uid");
    sb.created_at = now_s();
    if (p["labels"].is_object()) sb.labels = p["labels"].as_object();
    std::lock_guard<std::mutex> l(mu_);
    sandboxes_[sb.id] = sb;
    return Json(sb.id);
  }

  Json stop_pod_sandbox(const Json& p) {
    const std::string id = p.get("sandbox_id");
    std::vector<std::string> cids;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = sandboxes_.find(id);
      if (it != sandboxes_.end()) it->second.state = "SANDBOX_NOTREADY";
      for (auto& kv : containers_)
        if (kv.second.sandbox_id == id) cids.push_back(kv.first);
    }
    for (auto& cid : cids) kill_container(cid, 5.0);
    return Json();
  }

  Json remove_pod_sandbox(const Json& p) {
    const std::string id = p.get("sandbox_id");
    // stop before erase (ProcessRuntime contract: remove implies stop) —
    // erasing a RUNNING container would orphan its process tree forever
    std::vector<std::string> cids;
    {
      std::lock_guard<std::mutex> l(mu_);
      for (auto& kv : containers_)
        if (kv.second.sandbox_id == id) cids.push_back(kv.first);
    }
    for (auto& cid : cids) kill_container(cid, 2.0);
    std::lock_guard<std::mutex> l(mu_);
    for (auto it = containers_.begin(); it != containers_.end();)
      it = (it->second.sandbox_id == id) ? containers_.erase(it) : ++it;
    sandboxes_.erase(id);
    return Json();
  }

  Json list_pod_sandboxes() {
    std::lock_guard<std::mutex> l(mu_);
    JsonArray out;
    for (auto& kv : sandboxes_) {
      JsonObject o;
      const Sandbox& s = kv.second;
      o["id"] = Json(s.id);
      o["pod_name"] = Json(s.pod_name);
      o["pod_namespace"] = Json(s.pod_namespace);
      o["pod_uid"] = Json(s.pod_uid);
      o["state"] = Json(s.state);
      o["created_at"] = Json(s.created_at);
      o["labels"] = Json(s.labels);
      out.push_back(Json(o));
    }
    return Json(out);
  }

  // ----------------------------------------------------------- containers

  Json create_container(const Json& p) {
    const Json& cfg = p["config"];
    Container c;
    c.id = gen_id("ct");
    c.sandbox_id = p.get("sandbox_id");
    {
      std::lock_guard<std::mutex> l(mu_);
      if (!sandboxes_.count(c.sandbox_id))
        throw std::runtime_error("no such sandbox " + c.sandbox_id);
    }
    c.name = cfg.get("name");
    c.image = cfg.get("image");
    for (const auto& v : cfg["command"].as_array())
      c.argv.push_back(v.as_string());
    for (const auto& v : cfg["args"].as_array())
      c.argv.push_back(v.as_string());
    if (c.argv.empty())
      throw std::runtime_error("container " + c.name +
                               ": command required for process runtime");
    if (cfg["env"].is_object()) c.env = cfg["env"].as_object();
    c.working_dir = cfg.get("working_dir");
    for (const auto& v : cfg["cgroup_procs_files"].as_array())
      c.cgroup_procs_files.push_back(v.as_string());
    for (const auto& v : cfg["cpuset"].as_array())
      c.cpuset.push_back((int)v.as_int());
    if (cfg["mounts"].is_array()) c.mounts = cfg["mounts"].as_array();
    if (!cfg["run_as_user"].is_null())
      c.run_as_user = (long)cfg["run_as_user"].as_int();
    if (!cfg["run_as_group"].is_null())
      c.run_as_group = (long)cfg["run_as_group"].as_int();
    // the child defaults gid to run_as_user when run_as_group is unset
    // (see the setgid in exec_child) — the create-time check must model
    // the same defaulting, or that combination passes create and then
    // fails setgid at start as an opaque exit-126 crash
    const long target_gid =
        c.run_as_group >= 0 ? c.run_as_group : c.run_as_user;
    if (geteuid() != 0 &&
        ((c.run_as_user >= 0 && (uid_t)c.run_as_user != geteuid()) ||
         (target_gid >= 0 && (gid_t)target_gid != getegid())))
      // refuse at CREATE, not silently at start: running a workload as
      // the wrong identity would be a security lie
      throw std::runtime_error("runAsUser/runAsGroup requires a root runtime");
    c.log_path = root_ + "/logs/" + c.id + ".log";
    std::lock_guard<std::mutex> l(mu_);
    containers_[c.id] = c;
    return Json(c.id);
  }

  Json start_container(const Json& p) {
    const std::string id = p.get("container_id");
    Container snapshot;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(id);
      if (it == containers_.end())
        throw std::runtime_error("no such container " + id);
      if (it->second.state != "CREATED")
        throw std::runtime_error("container " + id + " already started");
      // claim under the lock: the fork below runs unlocked, and a second
      // concurrent start for the same id must not also pass the CREATED
      // check (it would leak one forked process)
      it->second.state = "STARTING";
      snapshot = it->second;
    }
    // from here on, any failure before the pid is recorded must surrender
    // the claim so a retry can start the container
    auto unclaim = [&]() {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(id);
      if (it != containers_.end() && it->second.state == "STARTING")
        it->second.state = "CREATED";
    };
    // ---- everything allocated BEFORE fork: a multithreaded parent must
    // not malloc between fork and exec (another thread may hold the heap
    // lock at fork time and the child would deadlock — same reason the
    // Python runtime uses an sh preamble instead of preexec_fn)
    std::vector<std::string> argv_store = snapshot.argv;
    if (!snapshot.mounts.empty() && mount_ns_) {
      // unshare+bind preamble (parity with runtime.py _wrap_with_mounts):
      // binds live in a private mount ns; mkdir of mount points persists
      std::string script = "set -e\n";
      for (const auto& mj : snapshot.mounts) {
        const JsonObject& m = mj.as_object();
        auto get = [&](const char* k) {
          auto it2 = m.find(k);
          return it2 == m.end() ? std::string() : it2->second.as_string();
        };
        std::string s = get("host_path"), d = get("container_path");
        if (s.empty() || d.empty()) continue;
        struct stat st;
        if (stat(s.c_str(), &st) != 0) continue;
        if (S_ISDIR(st.st_mode))
          script += "mkdir -p " + sh_quote(d) + "\n";
        else
          script += "mkdir -p $(dirname " + sh_quote(d) + ") && touch " +
                    sh_quote(d) + "\n";
        script += "mount --bind " + sh_quote(s) + " " + sh_quote(d) + "\n";
        auto ro = m.find("read_only");
        if (ro != m.end() && ro->second.as_bool())
          script += "mount -o remount,ro,bind " + sh_quote(d) + "\n";
      }
      script += "exec \"$@\"";
      std::vector<std::string> wrapped = {
          "unshare", "--mount", "--propagation", "private", "--",
          "sh", "-c", script, "sh"};
      wrapped.insert(wrapped.end(), argv_store.begin(), argv_store.end());
      argv_store = std::move(wrapped);
    }
    std::vector<char*> argv;
    for (auto& a : argv_store) argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    std::vector<std::string> env_store;
    for (char** e = environ; *e; ++e) {
      const char* eq = strchr(*e, '=');
      if (!eq) continue;
      std::string key(*e, eq - *e);
      if (!snapshot.env.count(key)) env_store.push_back(*e);
    }
    for (auto& kv : snapshot.env)
      env_store.push_back(kv.first + "=" + kv.second.as_string());
    for (const auto& mj : snapshot.mounts) {
      // path-agnostic consumption parity: KTPU_VOLUME_<NAME>=host_path
      const JsonObject& m = mj.as_object();
      auto itn = m.find("name");
      auto ith = m.find("host_path");
      if (itn == m.end() || ith == m.end()) continue;
      std::string name = itn->second.as_string();
      for (auto& ch : name) {
        if (ch == '-' || ch == '.') ch = '_';
        ch = toupper((unsigned char)ch);
      }
      if (!name.empty())
        env_store.push_back("KTPU_VOLUME_" + name + "=" +
                            ith->second.as_string());
    }
    std::vector<char*> envp;
    for (auto& s : env_store) envp.push_back(const_cast<char*>(s.c_str()));
    envp.push_back(nullptr);
    std::vector<int> cgroup_fds;
    for (const auto& pf : snapshot.cgroup_procs_files) {
      int fd = open(pf.c_str(), O_WRONLY);
      if (fd >= 0) cgroup_fds.push_back(fd);
    }
    int logfd = open(snapshot.log_path.c_str(),
                     O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (logfd < 0) {
      for (int fd : cgroup_fds) close(fd);
      unclaim();
      throw std::runtime_error("cannot open log file");
    }
    const char* wd =
        snapshot.working_dir.empty() ? nullptr : snapshot.working_dir.c_str();
    cpu_set_t cpuset;
    CPU_ZERO(&cpuset);
    for (int cn : snapshot.cpuset) CPU_SET(cn, &cpuset);
    pid_t pid = fork();
    if (pid < 0) {
      close(logfd);
      for (int fd : cgroup_fds) close(fd);
      unclaim();
      throw std::runtime_error("fork failed");
    }
    if (pid == 0) {
      // child: async-signal-safe syscalls only — no allocation
      setsid();
      char pidbuf[16];
      int n = snprintf(pidbuf, sizeof pidbuf, "%d", (int)getpid());
      for (int fd : cgroup_fds) {
        if (write(fd, pidbuf, n) < 0) { /* best effort */ }
        close(fd);
      }
      if (!snapshot.cpuset.empty())
        sched_setaffinity(0, sizeof cpuset, &cpuset);
      dup2(logfd, 1);
      dup2(logfd, 2);
      if (wd && chdir(wd) != 0) _exit(127);
      // drop privileges LAST (after cgroup join, which needed root):
      // gid first — setuid would forfeit the right to setgid.  Skip any
      // part already satisfied (a non-root runtime asked for its own
      // uid/gid must not fail a setgid it cannot and need not perform).
      {
        long g = snapshot.run_as_group;
        if (g < 0 && snapshot.run_as_user >= 0) g = snapshot.run_as_user;
        bool need_gid = g >= 0 && (gid_t)g != getegid();
        bool need_uid = snapshot.run_as_user >= 0 &&
                        (uid_t)snapshot.run_as_user != geteuid();
        if (need_gid || need_uid) {
          if (setgroups(0, nullptr) != 0 && geteuid() == 0) _exit(126);
          if (need_gid && setgid((gid_t)g) != 0) _exit(126);
          if (need_uid &&
              setuid((uid_t)snapshot.run_as_user) != 0) _exit(126);
        }
      }
      execvpe(argv[0], argv.data(), envp.data());
      dprintf(2, "exec failed: %s\n", strerror(errno));
      _exit(127);
    }
    close(logfd);
    for (int fd : cgroup_fds) close(fd);
    std::lock_guard<std::mutex> l(mu_);
    auto it = containers_.find(id);
    if (it == containers_.end()) {
      // removed concurrently: never resurrect a ghost entry — reap the
      // freshly forked process instead
      kill(-pid, SIGKILL);
      int status = 0;
      waitpid(pid, &status, 0);
      throw std::runtime_error("container " + id + " removed during start");
    }
    it->second.pid = pid;
    it->second.state = "RUNNING";
    it->second.started_at = now_s();
    return Json();
  }

  void reap_locked(Container& c) {
    if (c.state != "RUNNING" || c.pid <= 0) return;
    int status = 0;
    pid_t r = waitpid(c.pid, &status, WNOHANG);
    if (r == c.pid) {
      c.state = "EXITED";
      c.has_exit = true;
      c.exit_code = WIFEXITED(status) ? WEXITSTATUS(status)
                                      : 128 + WTERMSIG(status);
      c.finished_at = now_s();
    }
  }

  void kill_container(const std::string& id, double timeout) {
    pid_t pid = -1;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(id);
      if (it == containers_.end()) return;
      reap_locked(it->second);
      if (it->second.state != "RUNNING") return;
      pid = it->second.pid;
    }
    if (pid > 0) kill(-pid, SIGTERM);
    double deadline = now_s() + timeout;
    while (now_s() < deadline) {
      {
        std::lock_guard<std::mutex> l(mu_);
        auto it = containers_.find(id);
        if (it == containers_.end()) return;
        reap_locked(it->second);
        if (it->second.state != "RUNNING") return;
      }
      usleep(50 * 1000);
    }
    if (pid > 0) kill(-pid, SIGKILL);
    // bounded post-SIGKILL reap: never hold mu_ across a blocking waitpid —
    // a child lingering in uninterruptible sleep would stall every CRI RPC.
    // reap_locked (WNOHANG) under short lock holds instead.
    double kill_deadline = now_s() + 2.0;
    while (now_s() < kill_deadline) {
      {
        std::lock_guard<std::mutex> l(mu_);
        auto it = containers_.find(id);
        if (it == containers_.end()) return;
        reap_locked(it->second);
        if (it->second.state != "RUNNING") return;
      }
      usleep(10 * 1000);
    }
    std::lock_guard<std::mutex> l(mu_);
    auto it = containers_.find(id);
    if (it != containers_.end() && it->second.state == "RUNNING") {
      // still not reaped (D-state straggler): record the kill as the
      // outcome and hand the eventual zombie to a detached reaper so the
      // pid table entry is released whenever the kernel lets go
      pid_t stuck = it->second.pid;
      it->second.state = "EXITED";
      it->second.has_exit = true;
      it->second.exit_code = 137;
      it->second.finished_at = now_s();
      std::thread([stuck] {
        int status = 0;
        waitpid(stuck, &status, 0);
      }).detach();
    }
  }

  Json stop_container(const Json& p) {
    kill_container(p.get("container_id"),
                   p["timeout"].as_number(10.0));
    return Json();
  }

  Json remove_container(const Json& p) {
    const std::string id = p.get("container_id");
    kill_container(id, 1.0);
    std::lock_guard<std::mutex> l(mu_);
    auto it = containers_.find(id);
    if (it != containers_.end()) {
      unlink(it->second.log_path.c_str());
      containers_.erase(it);
    }
    return Json();
  }

  JsonObject record(const Container& c) {
    JsonObject o;
    o["id"] = Json(c.id);
    o["sandbox_id"] = Json(c.sandbox_id);
    o["name"] = Json(c.name);
    o["image"] = Json(c.image);
    // STARTING is an internal claim (start in flight, pid not yet
    // recorded); on the wire it is still a created-not-running container
    o["state"] = Json(c.state == "STARTING" ? std::string("CREATED")
                                            : c.state);
    o["exit_code"] = c.has_exit ? Json(c.exit_code) : Json();
    o["started_at"] = Json(c.started_at);
    o["finished_at"] = Json(c.finished_at);
    o["restart_count"] = Json(c.restart_count);
    o["log_path"] = Json(c.log_path);
    return o;
  }

  Json list_containers() {
    std::lock_guard<std::mutex> l(mu_);
    JsonArray out;
    for (auto& kv : containers_) {
      reap_locked(kv.second);
      out.push_back(Json(record(kv.second)));
    }
    return Json(out);
  }

  Json container_status(const Json& p) {
    std::lock_guard<std::mutex> l(mu_);
    auto it = containers_.find(p.get("container_id"));
    if (it == containers_.end()) return Json();
    reap_locked(it->second);
    return Json(record(it->second));
  }

  Json read_log(const Json& p) {
    std::string path;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(p.get("container_id"));
      if (it == containers_.end()) return Json(std::string());
      path = it->second.log_path;
    }
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return Json(std::string());
    std::string out;
    char buf[65536];
    size_t n;
    while ((n = fread(buf, 1, sizeof buf, f)) > 0) out.append(buf, n);
    fclose(f);
    int64_t tail = p["tail"].as_int(0);
    if (tail > 0) {
      // keep the last `tail` lines
      size_t pos = out.size();
      int64_t lines = 0;
      while (pos > 0 && lines < tail) {
        --pos;
        if (out[pos] == '\n' && pos != out.size() - 1) ++lines;
        if (lines == tail) { ++pos; break; }
      }
      out = out.substr(pos);
    }
    return Json(out);
  }

  Json container_stats(const Json& p) {
    const std::string id = p.get("container_id");
    JsonObject o;
    o["cpu"] = Json(0.0);
    o["memory"] = Json(0.0);
    std::lock_guard<std::mutex> l(mu_);
    auto it = containers_.find(id);
    if (it == containers_.end() || it->second.state != "RUNNING")
      return Json(o);
    Container& c = it->second;
    char path[64];
    snprintf(path, sizeof path, "/proc/%d/statm", (int)c.pid);
    FILE* f = fopen(path, "r");
    if (f) {
      long size = 0, resident = 0;
      if (fscanf(f, "%ld %ld", &size, &resident) == 2)
        o["memory"] = Json((double)resident * sysconf(_SC_PAGESIZE));
      fclose(f);
    }
    // cpu cores = d(utime+stime)/dt (ProcessRuntime/cadvisor parity)
    snprintf(path, sizeof path, "/proc/%d/stat", (int)c.pid);
    f = fopen(path, "r");
    if (f) {
      char statbuf[1024];
      if (fgets(statbuf, sizeof statbuf, f)) {
        // fields after the parenthesized comm: state ppid pgrp session
        // tty tpgid flags minflt cminflt majflt cmajflt utime stime ...
        char* close_paren = strrchr(statbuf, ')');
        if (close_paren) {
          unsigned long utime = 0, stime = 0;
          int field = 0;
          char* tok = strtok(close_paren + 1, " ");
          while (tok && field < 13) {
            ++field;
            if (field == 12) utime = strtoul(tok, nullptr, 10);
            if (field == 13) stime = strtoul(tok, nullptr, 10);
            tok = strtok(nullptr, " ");
          }
          double ticks = (double)(utime + stime);
          double now = now_s();
          if (c.cpu_ticks_prev >= 0 && now > c.cpu_sample_ts) {
            double hz = (double)sysconf(_SC_CLK_TCK);
            double cores = (ticks - c.cpu_ticks_prev) / hz /
                           (now - c.cpu_sample_ts);
            o["cpu"] = Json(cores < 0 ? 0.0 : cores);
          }
          c.cpu_ticks_prev = ticks;
          c.cpu_sample_ts = now;
        }
      }
      fclose(f);
    }
    return Json(o);
  }

  // ------------------------------------------------------------ exec/affinity

  Json exec_capture(const Json& p) {
    // ProcessRuntime parity: refuse non-running containers, bound the
    // whole exec at 10s, and (like start_container) allocate NOTHING
    // between fork and exec — argv/envp buffers are prepared up front.
    Container snapshot;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(p.get("container_id"));
      if (it == containers_.end())
        throw std::runtime_error("no such container");
      reap_locked(it->second);
      if (it->second.state != "RUNNING") {
        JsonObject o;
        o["exit_code"] = Json(-1);
        o["output"] = Json(std::string("container not running"));
        return Json(o);
      }
      snapshot = it->second;
    }
    std::vector<std::string> argv_store;
    for (const auto& v : p["command"].as_array())
      argv_store.push_back(v.as_string());
    if (argv_store.empty()) throw std::runtime_error("empty exec command");
    std::vector<char*> argv;
    for (auto& a : argv_store) argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    std::vector<std::string> env_store;
    for (char** e = environ; *e; ++e) {
      const char* eq = strchr(*e, '=');
      if (!eq) continue;
      std::string key(*e, eq - *e);
      if (!snapshot.env.count(key)) env_store.push_back(*e);
    }
    for (auto& kv : snapshot.env)
      env_store.push_back(kv.first + "=" + kv.second.as_string());
    std::vector<char*> envp;
    for (auto& s : env_store) envp.push_back(const_cast<char*>(s.c_str()));
    envp.push_back(nullptr);
    int fds[2];
    if (pipe(fds) != 0) throw std::runtime_error("pipe failed");
    pid_t pid = fork();
    if (pid < 0) {
      close(fds[0]);
      close(fds[1]);
      throw std::runtime_error("fork failed");
    }
    if (pid == 0) {
      close(fds[0]);
      dup2(fds[1], 1);
      dup2(fds[1], 2);
      execvpe(argv[0], argv.data(), envp.data());
      _exit(127);
    }
    close(fds[1]);
    // non-blocking drain with a 10s deadline (exec probes must not wedge a
    // server thread on a hung command or an inherited-pipe background child)
    fcntl(fds[0], F_SETFL, O_NONBLOCK);
    std::string out;
    char buf[4096];
    double deadline = now_s() + 10.0;
    bool timed_out = false;
    for (;;) {
      ssize_t n = read(fds[0], buf, sizeof buf);
      if (n > 0) {
        out.append(buf, n);
        continue;
      }
      if (n == 0) break;
      if (errno != EAGAIN && errno != EWOULDBLOCK) break;
      if (now_s() >= deadline) { timed_out = true; break; }
      usleep(20 * 1000);
    }
    close(fds[0]);
    int status = 0;
    if (timed_out) {
      kill(pid, SIGKILL);
      waitpid(pid, &status, 0);
      JsonObject o;
      o["exit_code"] = Json(-1);
      o["output"] = Json(out + "\n(exec timed out)");
      return Json(o);
    }
    waitpid(pid, &status, 0);
    JsonObject o;
    o["exit_code"] = Json(WIFEXITED(status) ? WEXITSTATUS(status) : 128);
    o["output"] = Json(out);
    return Json(o);
  }

  Json exec_in_container(const Json& p) {
    Json r = exec_capture(p);
    return r["exit_code"];
  }

  Json set_affinity(const Json& p) {
    pid_t pgid = -1;
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = containers_.find(p.get("container_id"));
      if (it == containers_.end() || it->second.state != "RUNNING")
        return Json(false);
      pgid = it->second.pid;  // setsid -> pgid == root pid
    }
    cpu_set_t set;
    CPU_ZERO(&set);
    for (const auto& v : p["cpus"].as_array()) CPU_SET((int)v.as_int(), &set);
    bool ok = false;
    DIR* proc = opendir("/proc");
    if (!proc) return Json(false);
    struct dirent* de;
    while ((de = readdir(proc)) != nullptr) {
      if (de->d_name[0] < '0' || de->d_name[0] > '9') continue;
      pid_t pid = atoi(de->d_name);
      if (getpgid(pid) != pgid) continue;
      char tdir[64];
      snprintf(tdir, sizeof tdir, "/proc/%d/task", (int)pid);
      DIR* tasks = opendir(tdir);
      if (!tasks) continue;
      struct dirent* te;
      while ((te = readdir(tasks)) != nullptr) {
        if (te->d_name[0] < '0' || te->d_name[0] > '9') continue;
        if (sched_setaffinity(atoi(te->d_name), sizeof set, &set) == 0)
          ok = true;
      }
      closedir(tasks);
    }
    closedir(proc);
    return Json(ok);
  }
};

// ----------------------------------------------------------------- server

void serve_conn(Runtime* rt, int fd) {
  std::string buf;
  char chunk[65536];
  for (;;) {
    ssize_t n = read(fd, chunk, sizeof chunk);
    if (n <= 0) break;
    buf.append(chunk, n);
    size_t nl;
    while ((nl = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, nl);
      buf.erase(0, nl + 1);
      if (line.empty()) continue;
      Json resp;
      JsonObject ro;
      try {
        Json req = Json::parse(line);
        ro["id"] = req["id"];
        ro["result"] = rt->dispatch(req.get("method"), req["params"]);
      } catch (const std::exception& e) {
        ro["error"] = Json(std::string(e.what()));
      }
      std::string out = Json(ro).dump() + "\n";
      size_t off = 0;
      while (off < out.size()) {
        ssize_t w = write(fd, out.data() + off, out.size() - off);
        if (w <= 0) { close(fd); return; }
        off += w;
      }
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  std::string socket_path = "/run/ktpu/cri.sock";
  std::string root = "/var/lib/ktpu";
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "--socket") == 0) socket_path = argv[++i];
    else if (strcmp(argv[i], "--root") == 0) root = argv[++i];
  }
  signal(SIGPIPE, SIG_IGN);

  Runtime rt(root);
  unlink(socket_path.c_str());
  // ensure the socket's parent dir exists
  std::string dir = socket_path.substr(0, socket_path.find_last_of('/'));
  if (!dir.empty()) {
    std::string cur;
    for (size_t i = 0; i < dir.size(); ++i) {
      cur += dir[i];
      if ((dir[i] == '/' && i > 0) || i + 1 == dir.size())
        mkdir(cur.c_str(), 0755);
    }
  }
  int srv = socket(AF_UNIX, SOCK_STREAM, 0);
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, socket_path.c_str(), sizeof addr.sun_path - 1);
  if (bind(srv, (sockaddr*)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 16);
  fprintf(stderr, "ktpu-cri-runtime: serving on %s (root %s)\n",
          socket_path.c_str(), root.c_str());
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::thread(serve_conn, &rt, fd).detach();
  }
  return 0;
}
