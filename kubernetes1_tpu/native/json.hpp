// Minimal JSON value + parser + serializer for the ktpu native components.
// The device-plugin wire protocol (deviceplugin/api.py) is newline-delimited
// single-line JSON frames, so this only needs correct RFC 8259 parsing of
// objects/arrays/strings/numbers/bools/null — no streaming, no comments.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ktpu {

class Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(double n) : type_(Type::Number), num_(n) {}
  Json(int n) : type_(Type::Number), num_(n) {}
  Json(int64_t n) : type_(Type::Number), num_(static_cast<double>(n)) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const { return str_; }
  const JsonArray& as_array() const { return arr_; }
  const JsonObject& as_object() const { return obj_; }
  JsonArray& arr() { return arr_; }
  JsonObject& obj() { return obj_; }

  // object field access; returns Null json for missing keys
  const Json& operator[](const std::string& key) const {
    static const Json null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }

  std::string get(const std::string& key, const std::string& dflt = "") const {
    const Json& v = (*this)[key];
    return v.is_string() ? v.as_string() : dflt;
  }

  void set(const std::string& key, Json v) { obj_[key] = std::move(v); }

  std::string dump() const {
    std::ostringstream out;
    dump_to(out);
    return out.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("trailing JSON data");
    return v;
  }

 private:
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;

  void dump_to(std::ostringstream& out) const {
    switch (type_) {
      case Type::Null: out << "null"; break;
      case Type::Bool: out << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (num_ == static_cast<int64_t>(num_)) {
          out << static_cast<int64_t>(num_);
        } else {
          out << num_;
        }
        break;
      }
      case Type::String: dump_string(out, str_); break;
      case Type::Array: {
        out << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) out << ',';
          arr_[i].dump_to(out);
        }
        out << ']';
        break;
      }
      case Type::Object: {
        out << '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) out << ',';
          first = false;
          dump_string(out, kv.first);
          out << ':';
          kv.second.dump_to(out);
        }
        out << '}';
        break;
      }
    }
  }

  static void dump_string(std::ostringstream& out, const std::string& s) {
    out << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': out << "\\\""; break;
        case '\\': out << "\\\\"; break;
        case '\n': out << "\\n"; break;
        case '\r': out << "\\r"; break;
        case '\t': out << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            out << buf;
          } else {
            out << c;
          }
      }
    }
    out << '"';
  }

  static void skip_ws(const std::string& t, size_t& pos) {
    while (pos < t.size() &&
           (t[pos] == ' ' || t[pos] == '\t' || t[pos] == '\n' || t[pos] == '\r'))
      ++pos;
  }

  static Json parse_value(const std::string& t, size_t& pos) {
    skip_ws(t, pos);
    if (pos >= t.size()) throw std::runtime_error("unexpected end of JSON");
    char c = t[pos];
    if (c == '{') return parse_object(t, pos);
    if (c == '[') return parse_array(t, pos);
    if (c == '"') return Json(parse_string(t, pos));
    if (t.compare(pos, 4, "true") == 0) { pos += 4; return Json(true); }
    if (t.compare(pos, 5, "false") == 0) { pos += 5; return Json(false); }
    if (t.compare(pos, 4, "null") == 0) { pos += 4; return Json(); }
    return parse_number(t, pos);
  }

  static Json parse_object(const std::string& t, size_t& pos) {
    JsonObject obj;
    ++pos;  // '{'
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == '}') { ++pos; return Json(std::move(obj)); }
    while (true) {
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != '"')
        throw std::runtime_error("expected object key");
      std::string key = parse_string(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != ':')
        throw std::runtime_error("expected ':'");
      ++pos;
      obj[key] = parse_value(t, pos);
      skip_ws(t, pos);
      if (pos < t.size() && t[pos] == ',') { ++pos; continue; }
      if (pos < t.size() && t[pos] == '}') { ++pos; break; }
      throw std::runtime_error("expected ',' or '}'");
    }
    return Json(std::move(obj));
  }

  static Json parse_array(const std::string& t, size_t& pos) {
    JsonArray arr;
    ++pos;  // '['
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == ']') { ++pos; return Json(std::move(arr)); }
    while (true) {
      arr.push_back(parse_value(t, pos));
      skip_ws(t, pos);
      if (pos < t.size() && t[pos] == ',') { ++pos; continue; }
      if (pos < t.size() && t[pos] == ']') { ++pos; break; }
      throw std::runtime_error("expected ',' or ']'");
    }
    return Json(std::move(arr));
  }

  static std::string parse_string(const std::string& t, size_t& pos) {
    ++pos;  // '"'
    std::string out;
    while (pos < t.size() && t[pos] != '"') {
      char c = t[pos];
      if (c == '\\') {
        ++pos;
        if (pos >= t.size()) throw std::runtime_error("bad escape");
        char e = t[pos];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos + 4 >= t.size()) throw std::runtime_error("bad \\u escape");
            unsigned code = std::stoul(t.substr(pos + 1, 4), nullptr, 16);
            pos += 4;
            // UTF-8 encode (surrogate pairs folded to replacement — the
            // plugin protocol carries ASCII identifiers)
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("bad escape");
        }
        ++pos;
      } else {
        out += c;
        ++pos;
      }
    }
    if (pos >= t.size()) throw std::runtime_error("unterminated string");
    ++pos;  // closing '"'
    return out;
  }

  static Json parse_number(const std::string& t, size_t& pos) {
    size_t start = pos;
    if (pos < t.size() && (t[pos] == '-' || t[pos] == '+')) ++pos;
    while (pos < t.size() &&
           (isdigit(static_cast<unsigned char>(t[pos])) || t[pos] == '.' ||
            t[pos] == 'e' || t[pos] == 'E' || t[pos] == '-' || t[pos] == '+'))
      ++pos;
    if (pos == start) throw std::runtime_error("invalid JSON value");
    return Json(std::stod(t.substr(start, pos - start)));
  }
};

}  // namespace ktpu
