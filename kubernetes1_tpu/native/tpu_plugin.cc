// ktpu-tpu-plugin — native libtpu device plugin.
//
// C++ implementation of the 4-RPC device-plugin protocol
// (deviceplugin/api.py; ref: pkg/kubelet/apis/deviceplugin/v1alpha/api.proto):
// GetPluginInfo, ListAndWatch (stream), AdmitPod, InitContainer over a unix
// socket at <plugin_dir>/google.com/tpu.sock, speaking newline-delimited
// JSON frames. This is the production-node counterpart of the Python
// TPUDevicePlugin (deviceplugin/tpu_plugin.py) — same discovery modes
// (KTPU_FAKE_TPUS or /dev/accel*), same ContainerSpec env injection, no
// Python runtime needed on TPU hosts.
//
// Build: make -C kubernetes1_tpu/native

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "json.hpp"

using ktpu::Json;
using ktpu::JsonArray;
using ktpu::JsonObject;

namespace {

constexpr const char* kResource = "google.com/tpu";
constexpr const char* kAttrType = "google.com/tpu/type";
constexpr const char* kAttrTopology = "google.com/tpu/topology";
constexpr const char* kAttrSlice = "google.com/tpu/slice";
constexpr const char* kAttrHostIndex = "google.com/tpu/host-index";
constexpr const char* kAttrCoords = "google.com/tpu/coords";
constexpr const char* kAttrDeviceIndex = "ktpu.io/device-index";
constexpr const char* kAttrDevicePath = "ktpu.io/device-path";

constexpr const char* kAnnWorkerId = "tpu.ktpu.io/worker-id";
constexpr const char* kAnnCoordinator = "tpu.ktpu.io/coordinator-address";
constexpr const char* kAnnWorkerHostnames = "tpu.ktpu.io/worker-hostnames";

struct Device {
  std::string id;
  std::string health = "Healthy";
  JsonObject attributes;

  Json to_json() const {
    JsonObject o;
    o["id"] = Json(id);
    o["health"] = Json(health);
    o["attributes"] = Json(attributes);
    return Json(o);
  }
};

std::string topology_for(size_t count) {
  switch (count) {
    case 1: return "1x1x1";
    case 2: return "2x1x1";
    case 4: return "2x2x1";
    case 8: return "2x2x2";
    default: return std::to_string(count) + "x1x1";
  }
}

std::string getenv_or(const char* name, const std::string& dflt) {
  const char* v = getenv(name);
  return v && *v ? std::string(v) : dflt;
}

std::vector<std::string> split(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::string cur;
  for (char c : s) {
    if (c == sep) { out.push_back(cur); cur.clear(); }
    else cur += c;
  }
  out.push_back(cur);
  return out;
}

// Fake inventory: KTPU_FAKE_TPUS="<type>:<count>:<slice>:<host_index>"
// (the kubemark-style zero-hardware path, same format as the Python plugin).
std::vector<Device> fake_devices(const std::string& spec) {
  auto parts = split(spec, ':');
  std::string type = parts.size() > 0 && !parts[0].empty() ? parts[0] : "v5e";
  int count = parts.size() > 1 && !parts[1].empty() ? atoi(parts[1].c_str()) : 4;
  std::string slice = parts.size() > 2 && !parts[2].empty() ? parts[2] : "slice-0";
  std::string host = parts.size() > 3 && !parts[3].empty() ? parts[3] : "0";
  std::vector<Device> devices;
  for (int i = 0; i < count; ++i) {
    Device d;
    d.id = slice + "-h" + host + "-chip" + std::to_string(i);
    d.attributes[kAttrType] = Json(type);
    d.attributes[kAttrSlice] = Json(slice);
    d.attributes[kAttrHostIndex] = Json(host);
    d.attributes[kAttrCoords] =
        Json(std::to_string(i % 2) + "," + std::to_string(i / 2) + ",0");
    d.attributes[kAttrTopology] = Json(topology_for(count));
    d.attributes[kAttrDeviceIndex] = Json(std::to_string(i));
    devices.push_back(std::move(d));
  }
  return devices;
}

// Real inventory: walk /dev/accel[0-9]* on a TPU VM (ref: the legacy GPU
// manager's /dev/nvidia* walk, pkg/kubelet/gpu/nvidia/nvidia_gpu_manager.go).
std::vector<Device> real_devices() {
  std::vector<std::string> paths;
  DIR* dir = opendir("/dev");
  if (dir) {
    struct dirent* ent;
    while ((ent = readdir(dir)) != nullptr) {
      std::string name = ent->d_name;
      if (name.rfind("accel", 0) == 0 && name.size() > 5 &&
          isdigit(static_cast<unsigned char>(name[5]))) {
        paths.push_back("/dev/" + name);
      }
    }
    closedir(dir);
  }
  std::sort(paths.begin(), paths.end());

  char hostname[256] = "tpu-host";
  gethostname(hostname, sizeof hostname);
  std::string accel_type = getenv_or("TPU_ACCELERATOR_TYPE", "v5e");
  std::string slice = getenv_or("TPU_SLICE_ID", getenv_or("TPU_NAME", "slice-0"));
  std::string host_index = getenv_or("TPU_WORKER_ID", "0");

  std::vector<Device> devices;
  for (size_t i = 0; i < paths.size(); ++i) {
    Device d;
    d.id = std::string(hostname) + "-accel" + std::to_string(i);
    d.attributes[kAttrType] = Json(split(accel_type, '-')[0]);
    d.attributes[kAttrSlice] = Json(slice);
    d.attributes[kAttrHostIndex] = Json(host_index);
    d.attributes[kAttrCoords] =
        Json(std::to_string(i % 2) + "," + std::to_string(i / 2) + ",0");
    d.attributes[kAttrTopology] = Json(topology_for(paths.size()));
    d.attributes[kAttrDeviceIndex] = Json(std::to_string(i));
    d.attributes[kAttrDevicePath] = Json(paths[i]);
    devices.push_back(std::move(d));
  }
  return devices;
}

class TPUPlugin {
 public:
  TPUPlugin() {
    std::string fake = getenv_or("KTPU_FAKE_TPUS", "");
    devices_ = fake.empty() ? real_devices() : fake_devices(fake);
  }

  size_t device_count() const { return devices_.size(); }

  Json get_plugin_info() {
    JsonObject o;
    o["name"] = Json(kResource);
    o["version"] = Json("v1");
    o["device_count"] = Json(static_cast<int64_t>(devices_.size()));
    o["native"] = Json(true);
    return Json(o);
  }

  Json list_devices() {
    std::lock_guard<std::mutex> lock(mu_);
    JsonArray arr;
    for (const auto& d : devices_) arr.push_back(d.to_json());
    return Json(arr);
  }

  // Re-check /dev nodes; returns true if any health flipped.
  bool check_health() {
    std::lock_guard<std::mutex> lock(mu_);
    bool changed = false;
    for (auto& d : devices_) {
      auto it = d.attributes.find(kAttrDevicePath);
      if (it == d.attributes.end()) continue;
      struct stat st;
      bool healthy = stat(it->second.as_string().c_str(), &st) == 0;
      std::string want = healthy ? "Healthy" : "Unhealthy";
      if (d.health != want) {
        d.health = want;
        changed = true;
      }
    }
    return changed;
  }

  // AdmitPod: verify the scheduler's assignment against local inventory
  // (ref: devicemanager manager.go:152-236).
  Json admit_pod(const Json& params) {
    std::lock_guard<std::mutex> lock(mu_);
    JsonObject resp;
    const Json& assignments = params["assignments"];
    if (assignments.is_object()) {
      for (const auto& kv : assignments.as_object()) {
        for (const auto& idj : kv.second.as_array()) {
          const std::string& id = idj.as_string();
          const Device* dev = find(id);
          if (dev == nullptr) {
            resp["allowed"] = Json(false);
            resp["reason"] = Json("device " + id + " not on this node");
            return Json(resp);
          }
          if (dev->health != "Healthy") {
            resp["allowed"] = Json(false);
            resp["reason"] = Json("device " + id + " unhealthy");
            return Json(resp);
          }
        }
      }
    }
    resp["allowed"] = Json(true);
    return Json(resp);
  }

  // InitContainer: build the injection ContainerSpec (ref: manager.go:245-291
  // -> device_run_container_options.go). Same env contract as the Python
  // plugin: TPU_VISIBLE_CHIPS, TPU_* geometry, megascale bootstrap.
  Json init_container(const Json& params) {
    std::lock_guard<std::mutex> lock(mu_);
    JsonObject envs, spec;
    JsonArray dev_specs;
    std::vector<std::string> indices;
    const Device* sample = nullptr;
    if (params["device_ids"].is_array()) {
      for (const auto& idj : params["device_ids"].as_array()) {
        const Device* dev = find(idj.as_string());
        if (dev == nullptr) continue;
        if (sample == nullptr) sample = dev;
        auto it = dev->attributes.find(kAttrDeviceIndex);
        indices.push_back(it != dev->attributes.end() ? it->second.as_string()
                                                      : "0");
        auto pathit = dev->attributes.find(kAttrDevicePath);
        if (pathit != dev->attributes.end()) {
          JsonObject ds;
          ds["host_path"] = pathit->second;
          ds["container_path"] = pathit->second;
          ds["permissions"] = Json("rw");
          dev_specs.push_back(Json(ds));
        }
      }
    }
    std::string joined;
    for (size_t i = 0; i < indices.size(); ++i) {
      if (i) joined += ",";
      joined += indices[i];
    }
    envs["TPU_VISIBLE_CHIPS"] = Json(joined);
    envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] =
        Json(std::to_string(indices.size()) + ",1,1");
    if (sample != nullptr) {
      auto attr = [&](const char* key) {
        auto it = sample->attributes.find(key);
        return it != sample->attributes.end() ? it->second.as_string()
                                              : std::string();
      };
      envs["TPU_ACCELERATOR_TYPE"] = Json(attr(kAttrType));
      envs["TPU_TOPOLOGY"] = Json(attr(kAttrTopology));
      envs["TPU_SLICE_ID"] = Json(attr(kAttrSlice));
      envs["TPU_HOST_INDEX"] = Json(attr(kAttrHostIndex));
    }
    const Json& anns = params["pod_annotations"];
    if (anns.is_object()) {
      std::string v;
      if (!(v = anns.get(kAnnWorkerId)).empty())
        envs["TPU_WORKER_ID"] = Json(v);
      if (!(v = anns.get(kAnnCoordinator)).empty())
        envs["JAX_COORDINATOR_ADDRESS"] = Json(v);
      if (!(v = anns.get(kAnnWorkerHostnames)).empty())
        envs["TPU_WORKER_HOSTNAMES"] = Json(v);
    }
    JsonObject annotations;
    annotations["tpu.ktpu.io/injected"] = Json("true");
    annotations["tpu.ktpu.io/plugin"] = Json("native");
    spec["envs"] = Json(envs);
    spec["mounts"] = Json(JsonArray{});
    spec["devices"] = Json(dev_specs);
    spec["annotations"] = Json(annotations);
    return Json(spec);
  }

 private:
  const Device* find(const std::string& id) {
    for (const auto& d : devices_)
      if (d.id == id) return &d;
    return nullptr;
  }

  std::mutex mu_;
  std::vector<Device> devices_;
};

std::atomic<bool> g_stop{false};

bool write_line(int fd, const std::string& payload) {
  std::string line = payload + "\n";
  size_t off = 0;
  while (off < line.size()) {
    ssize_t n = write(fd, line.data() + off, line.size() - off);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

// ListAndWatch: initial inventory immediately, then health re-checks every
// interval (endpoint.go:99-105 stream semantics).
void serve_stream(int fd, TPUPlugin& plugin, int64_t rid) {
  auto send = [&](const Json& devices) {
    JsonObject frame;
    frame["stream"] = Json(rid);
    JsonObject result;
    result["devices"] = devices;
    frame["result"] = Json(result);
    return write_line(fd, Json(frame).dump());
  };
  if (!send(plugin.list_devices())) return;
  while (!g_stop.load()) {
    for (int i = 0; i < 100 && !g_stop.load(); ++i)
      usleep(100 * 1000);  // 10s total, responsive to shutdown
    if (g_stop.load()) return;
    if (plugin.check_health()) {
      if (!send(plugin.list_devices())) return;
    }
  }
}

void serve_conn(int fd, TPUPlugin& plugin) {
  std::string buf;
  char chunk[4096];
  while (!g_stop.load()) {
    size_t nl;
    while ((nl = buf.find('\n')) == std::string::npos) {
      ssize_t n = read(fd, chunk, sizeof chunk);
      if (n <= 0) { close(fd); return; }
      buf.append(chunk, static_cast<size_t>(n));
    }
    std::string line = buf.substr(0, nl);
    buf.erase(0, nl + 1);
    Json req;
    try {
      req = Json::parse(line);
    } catch (const std::exception&) {
      break;
    }
    std::string method = req.get("method");
    int64_t rid = req["id"].as_int();
    if (method == "ListAndWatch") {
      serve_stream(fd, plugin, rid);
      close(fd);
      return;
    }
    JsonObject resp;
    resp["id"] = Json(rid);
    try {
      if (method == "GetPluginInfo") resp["result"] = plugin.get_plugin_info();
      else if (method == "AdmitPod") resp["result"] = plugin.admit_pod(req["params"]);
      else if (method == "InitContainer")
        resp["result"] = plugin.init_container(req["params"]);
      else resp["error"] = Json("unknown method " + method);
    } catch (const std::exception& e) {
      resp["error"] = Json(e.what());
    }
    if (!write_line(fd, Json(resp).dump())) break;
  }
  close(fd);
}

int make_dirs(const std::string& path) {
  std::string cur;
  for (const auto& part : split(path, '/')) {
    if (part.empty()) { cur = "/"; continue; }
    cur += (cur.empty() || cur.back() == '/') ? part : "/" + part;
    mkdir(cur.c_str(), 0755);
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string plugin_dir = getenv_or("KTPU_PLUGIN_DIR", "/var/lib/ktpu/device-plugins");
  for (int i = 1; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--plugin-dir") == 0) plugin_dir = argv[i + 1];
  }
  signal(SIGPIPE, SIG_IGN);

  std::string sock_dir = plugin_dir + "/google.com";
  make_dirs(sock_dir);
  std::string sock_path = sock_dir + "/tpu.sock";
  unlink(sock_path.c_str());

  int srv = socket(AF_UNIX, SOCK_STREAM, 0);
  if (srv < 0) { perror("socket"); return 1; }
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  if (sock_path.size() >= sizeof addr.sun_path) {
    fprintf(stderr, "socket path too long: %s\n", sock_path.c_str());
    return 1;
  }
  strncpy(addr.sun_path, sock_path.c_str(), sizeof addr.sun_path - 1);
  if (bind(srv, reinterpret_cast<struct sockaddr*>(&addr), sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 16) != 0) { perror("listen"); return 1; }

  TPUPlugin plugin;
  printf("ktpu-tpu-plugin (native): advertising %zu chip(s) at %s\n",
         plugin.device_count(), sock_path.c_str());
  fflush(stdout);

  while (!g_stop.load()) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::thread([fd, &plugin] { serve_conn(fd, plugin); }).detach();
  }
  close(srv);
  unlink(sock_path.c_str());
  return 0;
}
