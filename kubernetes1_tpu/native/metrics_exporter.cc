// ktpu-metrics-exporter — native TPU metrics exporter.
//
// The TPU-side replacement for the reference README's DCGM → Prometheus GPU
// monitoring stack (README.md:57; SURVEY.md §5 observability): a small HTTP
// server exposing Prometheus text metrics about the host's TPU inventory —
// chip count, per-chip health, device-node presence — scraped by Prometheus
// from a DaemonSet on every TPU node. Discovery matches the device plugin
// (KTPU_FAKE_TPUS or /dev/accel*).
//
// GET /metrics  -> Prometheus text exposition
// GET /healthz  -> ok
//
// Build: make -C kubernetes1_tpu/native

#include <arpa/inet.h>
#include <dirent.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::string getenv_or(const char* name, const std::string& dflt) {
  const char* v = getenv(name);
  return v && *v ? std::string(v) : dflt;
}

struct Chip {
  std::string id;
  std::string type;
  std::string slice;
  bool healthy;
};

std::vector<Chip> discover() {
  std::vector<Chip> chips;
  std::string fake = getenv_or("KTPU_FAKE_TPUS", "");
  if (!fake.empty()) {
    // "<type>:<count>:<slice>:<host>"
    std::string type = "v5e", slice = "slice-0";
    int count = 4;
    std::istringstream ss(fake);
    std::string part;
    int idx = 0;
    while (std::getline(ss, part, ':')) {
      if (idx == 0 && !part.empty()) type = part;
      if (idx == 1 && !part.empty()) count = atoi(part.c_str());
      if (idx == 2 && !part.empty()) slice = part;
      ++idx;
    }
    for (int i = 0; i < count; ++i)
      chips.push_back({slice + "-chip" + std::to_string(i), type, slice, true});
    return chips;
  }
  std::string type = getenv_or("TPU_ACCELERATOR_TYPE", "v5e");
  std::string slice = getenv_or("TPU_SLICE_ID", "slice-0");
  DIR* dir = opendir("/dev");
  if (dir) {
    struct dirent* ent;
    std::vector<std::string> names;
    while ((ent = readdir(dir)) != nullptr) {
      std::string name = ent->d_name;
      if (name.rfind("accel", 0) == 0 && name.size() > 5 &&
          isdigit(static_cast<unsigned char>(name[5])))
        names.push_back(name);
    }
    closedir(dir);
    std::sort(names.begin(), names.end());
    for (const auto& name : names) {
      struct stat st;
      bool ok = stat(("/dev/" + name).c_str(), &st) == 0;
      chips.push_back({name, type, slice, ok});
    }
  }
  return chips;
}

std::string render_metrics() {
  auto chips = discover();
  char hostname[256] = "tpu-host";
  gethostname(hostname, sizeof hostname);
  std::ostringstream out;
  out << "# HELP ktpu_tpu_chips Total TPU chips discovered on this host\n"
      << "# TYPE ktpu_tpu_chips gauge\n"
      << "ktpu_tpu_chips{host=\"" << hostname << "\"} " << chips.size() << "\n"
      << "# HELP ktpu_tpu_chip_healthy Per-chip health (1 healthy, 0 unhealthy)\n"
      << "# TYPE ktpu_tpu_chip_healthy gauge\n";
  for (const auto& c : chips) {
    out << "ktpu_tpu_chip_healthy{host=\"" << hostname << "\",chip=\"" << c.id
        << "\",type=\"" << c.type << "\",slice=\"" << c.slice << "\"} "
        << (c.healthy ? 1 : 0) << "\n";
  }
  size_t healthy =
      std::count_if(chips.begin(), chips.end(), [](const Chip& c) { return c.healthy; });
  out << "# HELP ktpu_tpu_chips_healthy Healthy TPU chips on this host\n"
      << "# TYPE ktpu_tpu_chips_healthy gauge\n"
      << "ktpu_tpu_chips_healthy{host=\"" << hostname << "\"} " << healthy << "\n";
  return out.str();
}

void serve_conn(int fd) {
  char buf[4096];
  ssize_t n = read(fd, buf, sizeof buf - 1);
  if (n <= 0) { close(fd); return; }
  buf[n] = 0;
  std::string req(buf);
  std::string body, status = "200 OK", ctype = "text/plain; version=0.0.4";
  if (req.rfind("GET /metrics", 0) == 0) {
    body = render_metrics();
  } else if (req.rfind("GET /healthz", 0) == 0) {
    body = "ok\n";
  } else {
    status = "404 Not Found";
    body = "not found\n";
  }
  std::ostringstream resp;
  resp << "HTTP/1.1 " << status << "\r\nContent-Type: " << ctype
       << "\r\nContent-Length: " << body.size() << "\r\nConnection: close\r\n\r\n"
       << body;
  std::string payload = resp.str();
  size_t off = 0;
  while (off < payload.size()) {
    ssize_t w = write(fd, payload.data() + off, payload.size() - off);
    if (w <= 0) break;
    off += static_cast<size_t>(w);
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = atoi(getenv_or("KTPU_EXPORTER_PORT", "9101").c_str());
  for (int i = 1; i + 1 < argc; i += 2)
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
  signal(SIGPIPE, SIG_IGN);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv, reinterpret_cast<struct sockaddr*>(&addr), sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 16) != 0) { perror("listen"); return 1; }
  if (port == 0) {
    socklen_t len = sizeof addr;
    getsockname(srv, reinterpret_cast<struct sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
  }
  printf("ktpu-metrics-exporter (native): listening on 127.0.0.1:%d\n", port);
  fflush(stdout);

  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread([fd] { serve_conn(fd); }).detach();
  }
}
