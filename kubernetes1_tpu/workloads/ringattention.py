"""Ring attention: sequence/context parallelism over an `sp` mesh axis.

Long-context is first-class: a sequence too big for one chip's HBM is
sharded along its length; each device holds one Q/K/V block and K/V blocks
rotate around the ring with lax.ppermute (neighbor hops ride ICI), while a
running online-softmax accumulator (m, l, o) folds in each block — so the
full S x S attention is computed with S/n-sized tiles and no all-gather.

Causal masking is handled per (q_block, kv_block) pair from the blocks'
global offsets: kv block strictly behind -> dense, same block -> lower
triangle, ahead -> skipped (contributes nothing).

Written with shard_map so the collective schedule is explicit; everything
inside is jit-compatible (static shapes, fori_loop over ring steps).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, q_off, kv_off, causal):
    """One tile: q (B,Sq,H,hd) x k/v (B,Sk,H,hd) -> (o, m, l) partials.
    Returns unnormalised o with row max m and row sum l (f32)."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qi = q_off + jnp.arange(sq)[:, None]
        ki = kv_off + jnp.arange(sk)[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                      # (B,H,Sq)
    # guard fully-masked rows (m == NEG_INF) against NaN in exp
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                           # (B,H,Sq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
    return o.astype(jnp.float32), m_safe, l


def _merge(acc, new):
    """Fold a new (o, m, l) partial into the running accumulator."""
    o_a, m_a, l_a = acc
    o_n, m_n, l_n = new
    m = jnp.maximum(m_a, m_n)
    a = jnp.exp(m_a - m)
    b = jnp.exp(m_n - m)
    o = o_a * a[..., None].swapaxes(1, 2) + o_n * b[..., None].swapaxes(1, 2)
    l = l_a * a + l_n * b
    return o, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "sp", causal: bool = True) -> jax.Array:
    """q/k/v: (B, S, H, hd) with S sharded over `axis`. GQA allowed
    (H_kv divides H). Returns attention output sharded like q."""
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)

    # device-varying marker (shard_map VMA rules) landed with the
    # top-level shard_map; identity on older jax, which has no VMA types
    pvary = getattr(jax.lax, "pvary", lambda x, _axis: x)

    def local(q, k, v):
        # ring size is static mesh shape (axis_size is newer-jax only)
        n = mesh.shape[axis]
        idx = jax.lax.axis_index(axis)
        sq = q.shape[1]
        q_off = idx * sq
        # mark accumulators as device-varying over the ring axis so the
        # fori carry types match the body outputs (shard_map VMA rules)
        o0 = pvary(jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32), axis)
        m0 = pvary(jnp.full((q.shape[0], q.shape[2], sq), NEG_INF, jnp.float32), axis)
        l0 = pvary(jnp.zeros((q.shape[0], q.shape[2], sq), jnp.float32), axis)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(step, carry):
            acc, kc, vc = carry
            # kv block currently held came from device (idx - step) mod n
            src = jax.lax.rem(idx - step + n, n)
            kv_off = src * kc.shape[1]
            new = _block_attn(q, kc, vc, q_off, kv_off, causal)
            acc = _merge(acc, new)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return acc, kc, vc

        (o, m, l), _, _ = jax.lax.fori_loop(0, n, body, ((o0, m0, l0), k, v))
        l = jnp.maximum(l, 1e-20)
        return (o / l.swapaxes(1, 2)[..., None]).astype(q.dtype)

    spec = P(None, axis, None, None)
    # jax.shard_map landed as a top-level name after 0.4.x; older
    # installs ship it under jax.experimental (same semantics)
    smap = getattr(jax, "shard_map", None)
    if smap is None:
        from jax.experimental.shard_map import shard_map as smap
    fn = smap(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Dense single-device attention for correctness checks."""
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)
