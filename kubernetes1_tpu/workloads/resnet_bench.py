"""ResNet-50 training benchmark payload — runs INSIDE a scheduled pod.

This is the measured half of BASELINE.md's north-star metric ("JAX ResNet-50
imgs/sec/chip in a scheduled Job", ref test/e2e/scalability/density.go
pattern): bench.py submits a Job whose container command is

    python -m kubernetes1_tpu.workloads.resnet_bench --out <file>

so the number on the board is produced by the full stack — admission rewrote
the google.com/tpu limit, the scheduler picked the chip, the kubelet's
ProcessRuntime launched this process with the device plugin's injected env —
not by a bare script.

Reports imgs/sec (total and per chip) and model-flops MFU: FLOPs per step
come from XLA's own cost analysis of the compiled step (analytic fallback),
peak from the device kind.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .tpu_peaks import peak_flops_per_device

# Analytic fallback: ResNet-50 forward ≈ 4.1 GFLOP/img at 224x224 (counting
# a MAC as 2 FLOPs); a training step costs ~3x forward (fwd + 2x bwd).
RESNET50_TRAIN_FLOPS_PER_IMG_224 = 3 * 4.1e9


def run(batch: int, steps: int, size: int, warmup: int = 2,
        watchdog=None, profile: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from . import sharding as sh
    from .resnet import ResNetConfig, init_params, make_train_step

    devices = jax.devices()
    if watchdog is not None:
        watchdog.cancel()  # chip claim succeeded: stand down
    n_dev = len(devices)
    cfg = ResNetConfig()
    mesh = sh.auto_mesh()
    with sh.use_mesh(mesh):
        params = init_params(cfg, jax.random.key(0))
        tx = optax.sgd(0.1, momentum=0.9)
        opt_state = jax.jit(tx.init)(params)
        step = make_train_step(cfg, tx)
        rng = np.random.default_rng(0)
        # feed in the compute dtype: the stem conv reads the raw pixels, so a
        # f32 feed doubles the first (and largest-spatial) HBM read for free
        images = jnp.asarray(rng.normal(size=(batch, size, size, 3)), cfg.dtype)
        labels = jnp.asarray(rng.integers(0, cfg.num_classes, batch), jnp.int32)

        flops_per_step = None
        try:
            cost = step.lower(params, opt_state, images, labels).compile().cost_analysis()
            if cost and cost.get("flops"):
                flops_per_step = float(cost["flops"])
        except Exception as e:  # noqa: BLE001 — cost_analysis is best-effort on some backends
            print(f"resnet_bench: cost_analysis unavailable: {e}")
        if not flops_per_step:
            flops_per_step = RESNET50_TRAIN_FLOPS_PER_IMG_224 * batch * (size / 224.0) ** 2

        # barrier = float(loss), not block_until_ready: on the tunneled
        # single-chip platform block_until_ready after a manual
        # lower().compile() can return without fencing (see llama_bench),
        # and a D2H transfer of the result is an unambiguous barrier.
        t_compile0 = time.perf_counter()
        for _ in range(warmup):
            params, opt_state, loss = step(params, opt_state, images, labels)
        float(loss)
        compile_s = time.perf_counter() - t_compile0

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, images, labels)
        float(loss)
        wall = time.perf_counter() - t0

        prof = None
        if profile:
            import tempfile

            from .benchguard import collect_profile

            def one_step():
                nonlocal params, opt_state, loss
                params, opt_state, loss = step(params, opt_state,
                                               images, labels)
                float(loss)

            prof = collect_profile(
                one_step, tempfile.mkdtemp(prefix="resnet-prof-"))

    kind = devices[0].device_kind
    peak, granularity = peak_flops_per_device(devices[0])
    steps_per_sec = steps / wall
    imgs_per_sec = batch * steps_per_sec
    mfu = (flops_per_step * steps_per_sec / (peak * n_dev)) if peak else None
    return {
        "workload": "resnet50",
        "device_kind": kind,
        "platform": devices[0].platform,
        "n_devices": n_dev,
        "device_granularity": granularity,  # "chip" (v4+) or "core" (v2/v3)
        "batch": batch,
        "image_size": size,
        "steps": steps,
        "compile_s": round(compile_s, 2),
        "step_time_ms": round(1000 * wall / steps, 2),
        "imgs_per_sec": round(imgs_per_sec, 1),
        "imgs_per_sec_per_device": round(imgs_per_sec / n_dev, 1),
        "flops_per_step": flops_per_step,
        "peak_flops_per_device": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "final_loss": float(loss),
        "profile": prof,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="", help="write result JSON here")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--no-profile", action="store_true")
    ap.add_argument("--acquire-timeout", type=float, default=180.0,
                    help="hard exit if the chip claim hangs this long")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (the env var alone loses "
                         "to this image's sitecustomize axon hook)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .benchguard import device_acquisition_watchdog

    watchdog = device_acquisition_watchdog(args.out, args.acquire_timeout)
    try:
        result = run(args.batch, args.steps, args.size,
                     watchdog=watchdog, profile=not args.no_profile)
    except Exception as e:  # noqa: BLE001
        result = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(result), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(result, f)
        sys.exit(1)
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    main()
