"""Mesh + sharding helpers shared by the workloads.

The scaling-book recipe: pick a mesh, annotate shardings on params and
activations, let XLA insert the collectives.  Axes:

- dp    pure data parallelism (params replicated)
- fsdp  data parallelism with params sharded over the axis (ZeRO-3 style;
        XLA turns the annotations into all-gather/reduce-scatter)
- tp    megatron tensor parallelism (attention heads / ffn hidden)
- sp    sequence/context parallelism (ring attention, ringattention.py)

The framework's job (scheduler + device plugin) is to place each worker
process on the right host of a slice; inside the process these meshes map
onto ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(
    dp: int = 1,
    fsdp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = dp * fsdp * tp
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{fsdp}x{tp} needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(dp, fsdp, tp)
    return Mesh(arr, ("dp", "fsdp", "tp"))


def auto_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Sensible mesh for however many chips are visible: all-fsdp up to a
    host (<=8 chips), then dp across hosts."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fsdp = min(n, 8)
    dp = n // fsdp
    return make_mesh(dp=dp, fsdp=fsdp, tp=1, devices=devices[: dp * fsdp])


def use_mesh(mesh: Mesh):
    """Context manager activating `mesh` for sharding annotations —
    ``jax.set_mesh`` where it exists (newer jax), else the physical-mesh
    context (``with mesh:``, the pre-set_mesh idiom) so the workloads run
    on older jax installs too."""
    setter = getattr(jax, "set_mesh", None)
    return setter(mesh) if setter is not None else mesh


def active_mesh() -> Optional[Mesh]:
    """The mesh governing sharding annotations right now, or None.
    Newer jax tracks it as the abstract mesh (jax.set_mesh); older jax
    as the thread-resources physical mesh (`with mesh:`)."""
    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is not None:
        return getter()
    try:
        from jax.interpreters import pxla

        m = pxla.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        return None
    return None if m.empty else m


def constrain(x, spec: P):
    """with_sharding_constraint that no-ops when no mesh is active (so the
    same model code jits single-chip without a mesh context)."""
    m = active_mesh()
    if m is None or not m.axis_names:
        return x
    # drop axes the active mesh doesn't have (e.g. a pure-dp mesh)
    def filter_axes(entry):
        if entry is None:
            return None
        axes = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(a for a in axes if a in m.axis_names)
        return kept if kept else None

    spec = P(*(filter_axes(e) for e in spec))
    return jax.lax.with_sharding_constraint(x, spec)
