"""Single-chip JAX MNIST (BASELINE config 2: a pod requesting
google.com/tpu: 1, the device-plugin Allocate path).

The e2e value is the *orchestration* seam — the pod runs this module as
its container command with TPU_VISIBLE_CHIPS injected by the device
plugin — so the data is synthetic (zero-egress image): 10 Gaussian
clusters in 784-d, which an MLP separates to ~100% accuracy in a few
steps.  Ref workload analog: test/e2e/scheduling/nvidia-gpus.go (CUDA
vector add as the scheduled GPU proof).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def synthetic_mnist(n: int = 4096, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    # class centers are task constants (fixed seed); `seed` only varies samples
    centers = np.random.default_rng(42).normal(size=(10, 784)).astype(np.float32)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    x = centers[labels] + 0.5 * rng.normal(size=(n, 784)).astype(np.float32)
    return x, labels.astype(np.int32)


def init_params(key: jax.Array, hidden: int = 256) -> Dict[str, jax.Array]:
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (784, hidden), jnp.float32) / np.sqrt(784),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (hidden, 10), jnp.float32) / np.sqrt(hidden),
        "b2": jnp.zeros((10,), jnp.float32),
    }


def forward(params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, x, y) -> jax.Array:
    logits = forward(params, x)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))


def train(steps: int = 50, batch: int = 256, lr: float = 0.1,
          seed: int = 0) -> Tuple[float, float]:
    """Returns (final_loss, accuracy on fresh batch)."""
    x, y = synthetic_mnist(seed=seed)
    params = init_params(jax.random.key(seed))
    tx = optax.sgd(lr, momentum=0.9)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    loss = None
    for _ in range(steps):
        idx = rng.integers(0, len(x), batch)
        params, opt_state, loss = step(params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx]))

    xe, ye = synthetic_mnist(1024, seed=seed + 1)
    acc = float(jnp.mean(jnp.argmax(forward(params, jnp.asarray(xe)), -1) == jnp.asarray(ye)))
    return float(loss), acc


if __name__ == "__main__":
    loss, acc = train()
    print(f"mnist final loss={loss:.4f} acc={acc:.3f} on {jax.devices()[0].platform}")
