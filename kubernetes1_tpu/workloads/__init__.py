"""JAX training workloads the framework schedules onto TPU.

The reference is an orchestrator: its "workloads" are CUDA containers
(test/e2e/scheduling/nvidia-gpus.go runs a CUDA add; the README's headline
is scheduling GPU ML jobs).  The TPU-native equivalents live here — real
jax/pjit programs covering every BASELINE.json config:

- mnist     — single-chip JAX MNIST (config 2)
- resnet    — ResNet-50, data-parallel over a single-host mesh (config 3)
- llama     — Llama-3-style transformer with dp/fsdp/tp sharding, scanned
              layers, remat, bf16 (config 5; flagship model)
- bert      — BERT-large-class MLM encoder, same tp/fsdp treatment with
              bidirectional fused attention (config 4)
- ringattention — sequence-parallel blockwise attention over an `sp` mesh
              axis (long-context path; ppermute ring over ICI)

These run *inside* scheduled pods (ProcessRuntime containers) with the
TPU env injected by the device plugin; they are also imported directly by
bench.py and __graft_entry__.py.  Submodules import lazily so a container
running only mnist doesn't pay for llama/resnet at startup.
"""

import importlib

_SUBMODULES = ("mnist", "llama", "bert", "resnet", "ringattention",
               "sharding", "rl_actor")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
