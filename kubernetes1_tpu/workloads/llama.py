"""Llama-3-style decoder-only transformer, TPU-first.

This is the flagship workload (BASELINE configs 4/5: BERT-large-class and
Llama-3-8B training jobs on multi-host slices).  Design choices per the
TPU playbook rather than any torch reference:

- params live in a pytree with per-leaf PartitionSpecs (megatron tp on
  heads/ffn, fsdp on the remaining weight dim); jit consumes NamedShardings
  and XLA inserts all-gather/reduce-scatter/psum on ICI.
- layers are stacked and iterated with lax.scan — one trace/compile per
  layer body, static shapes throughout.
- compute in bfloat16, params + adam state in float32.
- jax.checkpoint (remat) on the layer body trades FLOPs for HBM.
- GQA + RoPE; causal attention via jax.nn.dot_product_attention (lowers to
  a fused TPU attention); the ring/sequence-parallel variant lives in
  ringattention.py.

Llama-3-8B = LlamaConfig(d_model=4096, n_layers=32, n_heads=32,
n_kv_heads=8, d_ff=14336, vocab=128256, rope_theta=500000).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "save_attn" keeps the attention outputs across the remat boundary so
    # the O(S^2) attention never recomputes in backward (measured +3-8%
    # MFU at seq 2048 on v5e); "full" recomputes everything
    remat_policy: str = "save_attn"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def llama_3_8b() -> LlamaConfig:
    return LlamaConfig(vocab=128256, d_model=4096, n_layers=32, n_heads=32,
                       n_kv_heads=8, d_ff=14336)


def tiny(vocab: int = 256, d_model: int = 64, n_layers: int = 2, n_heads: int = 4,
         n_kv_heads: int = 2, d_ff: int = 128, max_seq: int = 128) -> LlamaConfig:
    return LlamaConfig(vocab=vocab, d_model=d_model, n_layers=n_layers,
                       n_heads=n_heads, n_kv_heads=n_kv_heads, d_ff=d_ff,
                       max_seq=max_seq, remat=False)


# ------------------------------------------------------------------- params

def param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """PartitionSpecs per leaf.  Layer params carry a leading stacked-layer
    axis (for scan), which is never sharded."""
    return {
        # vocab sharded over BOTH model axes, d replicated: same per-device
        # bytes as a (tp, fsdp) 2-D tiling, but the embedding gather's
        # output then reshards to batch-sharded activations without the
        # mesh-transposed d-resharding that made XLA fall back to full
        # rematerialization (the MULTICHIP dryrun hard-fails on that)
        "embed": P(("tp", "fsdp"), None),     # (vocab, d)
        "layers": {
            "attn_norm": P(None, None),       # (L, d)
            "wq": P(None, "fsdp", "tp"),      # (L, d, n_heads*hd)
            "wk": P(None, "fsdp", "tp"),      # (L, d, n_kv*hd)
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),      # (L, n_heads*hd, d)
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tp"),  # (L, d, f)
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),  # (L, f, d)
        },
        "final_norm": P(None),                # (d,)
        "unembed": P("fsdp", "tp"),           # (d, vocab)
    }


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    k = jax.random.split(key, 9)
    d, hd = cfg.d_model, cfg.head_dim
    L = cfg.n_layers

    def norm_init(shape):
        return jnp.ones(shape, jnp.float32)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in))

    return {
        "embed": w(k[0], (cfg.vocab, d), d),
        "layers": {
            "attn_norm": norm_init((L, d)),
            "wq": w(k[1], (L, d, cfg.n_heads * hd), d),
            "wk": w(k[2], (L, d, cfg.n_kv_heads * hd), d),
            "wv": w(k[3], (L, d, cfg.n_kv_heads * hd), d),
            "wo": w(k[4], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
            "mlp_norm": norm_init((L, d)),
            "w_gate": w(k[5], (L, d, cfg.d_ff), d),
            "w_up": w(k[6], (L, d, cfg.d_ff), d),
            "w_down": w(k[7], (L, cfg.d_ff, d), cfg.d_ff),
        },
        "final_norm": norm_init((d,)),
        "unembed": w(k[8], (d, cfg.vocab), d),
    }


# ------------------------------------------------------------------ modules

def rmsnorm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: (B, S, H, hd); rotate pairs (even, odd) halves."""
    hd = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, hd // 2, dtype=jnp.float32) / (hd // 2))
    ang = positions[:, :, None].astype(jnp.float32) * freqs  # (B, S, hd/2)
    cos, sin = jnp.cos(ang)[:, :, None, :], jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal GQA via jax.nn.dot_product_attention (fused TPU lowering;
    handles grouped KV heads natively). q: (B,S,H,hd), k/v: (B,S,Hkv,hd)."""
    return jax.nn.dot_product_attention(q, k, v, is_causal=True)


def layer_fn(cfg: LlamaConfig, x: jax.Array, lp: Dict[str, jax.Array],
             positions: jax.Array) -> jax.Array:
    B, S, d = x.shape
    hd = cfg.head_dim
    h = rmsnorm(x, lp["attn_norm"])
    q = (h @ lp["wq"].astype(cfg.dtype)).reshape(B, S, cfg.n_heads, hd)
    k = (h @ lp["wk"].astype(cfg.dtype)).reshape(B, S, cfg.n_kv_heads, hd)
    v = (h @ lp["wv"].astype(cfg.dtype)).reshape(B, S, cfg.n_kv_heads, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    attn = attention(q, k, v).reshape(B, S, cfg.n_heads * hd)
    from jax.ad_checkpoint import checkpoint_name

    attn = checkpoint_name(attn, "attn_out")  # see LlamaConfig.remat_policy
    x = x + attn @ lp["wo"].astype(cfg.dtype)
    h = rmsnorm(x, lp["mlp_norm"])
    gate = jax.nn.silu(h @ lp["w_gate"].astype(cfg.dtype))
    up = h @ lp["w_up"].astype(cfg.dtype)
    x = x + (gate * up) @ lp["w_down"].astype(cfg.dtype)
    return x


def forward(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab) float32."""
    B, S = tokens.shape
    from . import sharding as sh

    x = params["embed"].astype(cfg.dtype)[tokens]
    x = sh.constrain(x, P(("dp", "fsdp"), None, None))
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    body = partial(layer_fn, cfg)
    if cfg.remat:
        policy = (jax.checkpoint_policies.save_only_these_names("attn_out")
                  if cfg.remat_policy == "save_attn" else None)
        body = jax.checkpoint(body, policy=policy)

    def scan_step(x, lp):
        return body(x, lp, positions), None

    x, _ = jax.lax.scan(scan_step, x, params["layers"])
    x = rmsnorm(x, params["final_norm"])
    logits = x @ params["unembed"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn(cfg: LlamaConfig, params, tokens) -> jax.Array:
    """Next-token cross entropy over tokens (B, S)."""
    logits = forward(cfg, params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# --------------------------------------------------------------- train step

def make_train_state(cfg: LlamaConfig, mesh: Mesh, lr: float = 3e-4,
                     seed: int = 0) -> Tuple[Dict[str, Any], Any, optax.GradientTransformation]:
    """Params + adam state, each leaf placed with its NamedSharding."""
    tx = optax.adamw(lr, weight_decay=0.1)
    specs = param_specs(cfg)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda s: isinstance(s, P))

    init = jax.jit(partial(init_params, cfg), out_shardings=shardings)
    params = init(jax.random.key(seed))
    # adam moments mirror the param tree; jit propagates param shardings
    opt_state = jax.jit(tx.init)(params)
    return params, opt_state, tx


def make_train_step(cfg: LlamaConfig, mesh: Mesh, tx: optax.GradientTransformation):
    from . import sharding as sh

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        tokens = sh.constrain(tokens, P(("dp", "fsdp"), None))
        loss, grads = jax.value_and_grad(partial(loss_fn, cfg))(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def train_demo(cfg: Optional[LlamaConfig] = None, mesh: Optional[Mesh] = None,
               steps: int = 3, batch: int = 8, seq: int = 64,
               lr: float = 3e-4) -> float:
    """Run a few steps on synthetic tokens; returns final loss. Used by the
    node e2e (scheduled as a Job container command) and the dryrun."""
    from . import sharding as sh

    cfg = cfg or tiny()
    mesh = mesh or sh.auto_mesh()
    with sh.use_mesh(mesh):
        params, opt_state, tx = make_train_state(cfg, mesh, lr=lr)
        step = make_train_step(cfg, mesh, tx)
        rng = np.random.default_rng(0)
        # one fixed batch: the demo shows the sharded step memorizing it
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)), jnp.int32)
        loss = None
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        return float(loss)


# ------------------------------------------------------------ decode serving

def greedy_decode(cfg: LlamaConfig, params: Dict[str, Any], step_fn,
                  tokens, max_new: int = 8) -> list:
    """Greedy continuation of a prompt: full re-forward per step (the
    tiny-config serving path — a KV cache is a perf lever, not a
    correctness one, and the serving bench's subject is the CONTROL
    plane: scrape -> custom metrics -> HPA).  `step_fn` is the jitted
    forward; returns the new token ids only."""
    toks = [int(x) % cfg.vocab for x in tokens] or [1]
    out = []
    for _ in range(max_new):
        window = toks[-cfg.max_seq:]
        arr = jnp.asarray([window], jnp.int32)
        logits = step_fn(params, arr)
        nxt = int(jnp.argmax(logits[0, -1]))
        toks.append(nxt)
        out.append(nxt)
    return out


# Sequence-length buckets for the batched forward: padding every step
# to the next bucket bounds XLA retraces at one compile per bucket (the
# batch dimension is always padded to the full slot count, so the shape
# space is |buckets|, not |active lengths|).
def _seq_bucket(n: int, max_seq: int) -> int:
    b = 8
    while b < n and b < max_seq:
        b *= 2
    return min(b, max_seq)


class SlotLease:
    """One admitted request's handle: a per-request token stream.  The
    engine pushes each decoded token as its step completes; ``None``
    terminates the stream (max_new reached or engine shutdown)."""

    def __init__(self, tokens, max_new: int):
        import queue as _queue

        self.prompt = list(tokens)
        self.max_new = max_new
        self.out: "_queue.Queue[Optional[int]]" = _queue.Queue()
        self.produced = 0
        self.slot: Optional[int] = None  # assigned at admission
        self.t_submit = 0.0
        self.t_last = 0.0

    def stream(self):
        """Yield tokens as the engine produces them (blocks between
        steps; ends at max_new)."""
        while True:
            tok = self.out.get()
            if tok is None:
                return
            yield tok

    def result(self, timeout: float = 60.0) -> list:
        """Drain the stream to a list (the non-streaming callers)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        toks = []
        for tok in self.stream():
            toks.append(tok)
            if _time.monotonic() > deadline:
                break
        return toks


class BatchEngine:
    """Continuous batching: ONE decode loop folds every in-flight
    request into a single forward per step, admitting new requests at
    step boundaries.  Capacity is the fixed slot pool (the KV-cache
    stand-in: a slot is the per-request state the batch carries), so
    saturation is visible as slot exhaustion — `ktpu_llama_slots_used`
    against `ktpu_llama_slots_total` — before it is visible as latency.

    Correctness: rows are RIGHT-padded (real tokens first), positions
    are arange, and attention is causal — so row i's logits at index
    len_i-1 are bit-identical to an unpadded single-row forward, and
    batched greedy decode equals sequential greedy decode token for
    token (tests/test_serving.py proves it against greedy_decode)."""

    def __init__(self, cfg: LlamaConfig, params, mesh, step_fn,
                 slots: int = 8, metrics=None):
        import threading as _threading

        from ..utils import locksan

        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self._step = step_fn
        self.slots = slots
        self._pending: list = []
        self._active: Dict[int, SlotLease] = {}
        self._cond = locksan.make_condition(name="BatchEngine._cond")
        self._stopping = False
        self.steps = 0
        self.tokens_out = 0
        self.metrics = metrics
        if metrics is not None:
            self.slots_total = metrics.gauge(
                "ktpu_llama_slots_total", "decode batch slot pool size")
            self.slots_used = metrics.gauge(
                "ktpu_llama_slots_used", "decode batch slots leased")
            self.occupancy = metrics.histogram(
                "ktpu_llama_batch_occupancy",
                "active requests per decode step")
            self.token_latency = metrics.histogram(
                "ktpu_llama_token_latency_seconds",
                "per-token latency (inter-token gap; first = from admit)")
            self.slots_total.set(float(slots))
            self.slots_used.set(0.0)
        # one engine thread per server, not per connection/request: the
        # whole point is that N requests share this single decode loop
        self._thread = _threading.Thread(
            target=self._run, daemon=True, name="llama-batch-engine")
        self._thread.start()

    # ---------------------------------------------------------- intake

    def submit(self, tokens, max_new: int = 8) -> SlotLease:
        import time as _time

        lease = SlotLease([int(x) % self.cfg.vocab for x in tokens] or [1],
                          max_new)
        lease.t_submit = lease.t_last = _time.monotonic()
        with self._cond:
            self._pending.append(lease)
            self._cond.notify()
        return lease

    def stop(self):
        with self._cond:
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ loop

    def _admit_locked(self):
        """Step-boundary admission: lease free slots to waiting
        requests, FIFO."""
        free = [s for s in range(self.slots) if s not in self._active]
        while free and self._pending:
            lease = self._pending.pop(0)
            lease.slot = free.pop(0)
            self._active[lease.slot] = lease

    def _run(self):
        import time as _time

        import numpy as _np

        from . import sharding as sh

        while True:
            with self._cond:
                self._admit_locked()
                while not self._active and not self._stopping:
                    self._cond.wait(timeout=0.5)
                    self._admit_locked()
                if self._stopping:
                    for lease in list(self._active.values()):
                        lease.out.put(None)
                    for lease in self._pending:
                        lease.out.put(None)
                    self._active.clear()
                    self._pending.clear()
                    return
                batch = dict(self._active)
            if self.metrics is not None:
                self.slots_used.set(float(len(batch)))
                self.occupancy.observe(float(len(batch)))
            # one forward per step over every active row, right-padded
            rows = {}
            maxlen = 1
            for slot, lease in batch.items():
                toks = lease.prompt[-self.cfg.max_seq:]
                rows[slot] = toks
                maxlen = max(maxlen, len(toks))
            bucket = _seq_bucket(maxlen, self.cfg.max_seq)
            arr = _np.zeros((self.slots, bucket), _np.int32)
            for slot, toks in rows.items():
                arr[slot, :len(toks)] = toks
            with sh.use_mesh(self.mesh):
                logits = self._step(self.params, jnp.asarray(arr))
                picks = jnp.argmax(
                    logits[jnp.arange(self.slots),
                           jnp.asarray([len(rows.get(s, [1])) - 1
                                        for s in range(self.slots)])],
                    axis=-1)
            picks = _np.asarray(picks)
            now = _time.monotonic()
            self.steps += 1
            done = []
            for slot, lease in batch.items():
                nxt = int(picks[slot])
                lease.prompt.append(nxt)
                lease.produced += 1
                self.tokens_out += 1
                if self.metrics is not None:
                    self.token_latency.observe(now - lease.t_last)
                    self.metrics.mark("ktpu_llama_tokens_per_s")
                lease.t_last = now
                lease.out.put(nxt)
                if lease.produced >= lease.max_new:
                    lease.out.put(None)
                    done.append(slot)
            if done:
                with self._cond:
                    for slot in done:
                        self._active.pop(slot, None)
                    self._cond.notify()


class DecodeServer:
    """The llama serving half: an HTTP decode endpoint plus the pod
    /metrics surface the kubelet's scrape agent lifts into
    PodCustomMetrics (obs/appmetrics contract) — QPS, in-flight
    requests, request-latency histograms, and (with batching) the
    slot-pool saturation gauges, the workload SLIs the HPA's Pods-type
    metric specs scale a serving Deployment on.

        POST /generate  {"tokens": [...], "max_new": N} -> {"tokens": [...]}
                        {"stream": true} streams ndjson token lines
                        ({"token": t} per decode step) over chunked
                        transfer encoding instead
        GET  /metrics   prometheus text (appmetrics registry)
        GET  /healthz

    ``batching=True`` (default; env KTPU_LLAMA_BATCHING=0 disables)
    routes requests through the continuous-batching engine — N
    concurrent requests share one forward per step.  ``batching=False``
    keeps the sequential one-request-per-forward baseline, the A/B arm
    the bench's tokens/s comparison runs against.
    """

    def __init__(self, cfg: Optional[LlamaConfig] = None, port: int = 0,
                 seed: int = 0, batching: Optional[bool] = None,
                 slots: int = 8):
        import os as _os

        from . import sharding as sh
        from ..obs.appmetrics import AppMetrics

        self.cfg = cfg or tiny()
        self.mesh = sh.auto_mesh()
        with sh.use_mesh(self.mesh):
            self.params = jax.jit(partial(init_params, self.cfg))(
                jax.random.key(seed))
        self._step = jax.jit(partial(forward, self.cfg))
        self.metrics = AppMetrics()
        self.requests_total = self.metrics.counter(
            "ktpu_llama_requests_total", "decode requests served")
        self.errors_total = self.metrics.counter(
            "ktpu_llama_request_errors_total", "malformed decode requests")
        self.inflight = self.metrics.gauge(
            "ktpu_llama_inflight", "decode requests currently in flight")
        self.latency = self.metrics.histogram(
            "ktpu_llama_request_latency_seconds", "decode request latency")
        if batching is None:
            batching = _os.environ.get("KTPU_LLAMA_BATCHING", "1") != "0"
        self.batching = batching
        self.engine: Optional[BatchEngine] = None
        if batching:
            self.engine = BatchEngine(self.cfg, self.params, self.mesh,
                                      self._step, slots=slots,
                                      metrics=self.metrics)
        self._port = port
        self._srv = None

    def generate(self, tokens, max_new: int = 8) -> list:
        import time as _time

        from . import sharding as sh

        t0 = _time.monotonic()
        self.inflight.inc()
        try:
            if self.engine is not None:
                return self.engine.submit(tokens, max_new).result()
            with sh.use_mesh(self.mesh):
                return greedy_decode(self.cfg, self.params, self._step,
                                     tokens, max_new=max_new)
        finally:
            self.inflight.inc(-1)
            self.requests_total.inc()
            self.metrics.mark("ktpu_llama_qps")
            self.latency.observe(_time.monotonic() - t0)

    def generate_stream(self, tokens, max_new: int = 8) -> SlotLease:
        """Streaming entry: returns the lease whose .stream() yields
        tokens at step cadence (batching only — the sequential arm has
        no step boundary to stream at)."""
        if self.engine is None:
            raise RuntimeError("streaming requires batching=True")
        return self.engine.submit(tokens, max_new)

    def warmup(self, tokens=(1, 2, 3), max_new: int = 4):
        """Pay the XLA compile for the given request shape OUTSIDE the
        SLI histograms: the first decode of each context length traces
        and compiles (seconds on CPU), and the latency histogram is
        cumulative — an un-warmed first request would sit in the p99
        for the process's whole life and fail any serving SLO judged
        against it.  With batching on, this pays the (slots, bucket)
        batch shapes the engine will step through."""
        from . import sharding as sh

        if self.engine is not None:
            self.engine.submit(list(tokens), max_new).result()
            return
        with sh.use_mesh(self.mesh):
            greedy_decode(self.cfg, self.params, self._step, list(tokens),
                          max_new=max_new)

    # ------------------------------------------------------------- server

    def start(self) -> "DecodeServer":
        import json as _json
        import threading as _threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, body: bytes, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    self._send(200, server.metrics.render().encode(),
                               ctype="text/plain; version=0.0.4")
                elif self.path.startswith("/healthz"):
                    self._send(200, b'{"status":"ok"}')
                else:
                    self._send(404, b'{"error":"unknown path"}')

            def do_POST(self):
                if not self.path.startswith("/generate"):
                    self._send(404, b'{"error":"unknown path"}')
                    return
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    req = _json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise TypeError("body must be a JSON object")
                    toks = [int(x) for x in (req.get("tokens") or [])]
                    max_new = min(64, int(req.get("max_new") or 8))
                    stream = bool(req.get("stream"))
                except (ValueError, TypeError):
                    server.errors_total.inc()
                    self._send(400, b'{"error":"bad request"}')
                    return
                if stream and server.engine is not None:
                    self._stream(toks, max_new)
                    return
                out = server.generate(toks, max_new=max_new)
                self._send(200, _json.dumps({"tokens": out}).encode())

            def _stream(self, toks, max_new: int):
                """Per-token streaming: one ndjson line per decode step
                over chunked transfer encoding (self-delimiting, so the
                byte-splicing proxy legs pass it through untouched)."""
                import time as _time

                t0 = _time.monotonic()
                server.inflight.inc()
                try:
                    lease = server.generate_stream(toks, max_new=max_new)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def chunk(payload: bytes):
                        self.wfile.write(b"%x\r\n%s\r\n"
                                         % (len(payload), payload))

                    for tok in lease.stream():
                        chunk(b'{"token":%d}\n' % tok)
                    chunk(b'{"done":true}\n')
                    self.wfile.write(b"0\r\n\r\n")
                finally:
                    server.inflight.inc(-1)
                    server.requests_total.inc()
                    server.metrics.mark("ktpu_llama_qps")
                    server.latency.observe(_time.monotonic() - t0)

        self._srv = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._srv.daemon_threads = True
        th = _threading.Thread(target=self._srv.serve_forever, daemon=True,
                               name="llama-decode")
        th.start()
        return self

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
        if self.engine is not None:
            self.engine.stop()
        self.metrics.stop()


def serving_deployment(name: str = "llama-serve", ns: str = "default",
                       replicas: int = 1, scrape_port: int = 0,
                       scrape_host: str = "", cpu: str = "100m"):
    """A Deployment of decode-server pods, template annotated with the
    obs.ktpu.io scrape contract so each replica's kubelet lifts its
    /metrics into PodCustomMetrics (in-process clusters pass the
    loopback host:port of a live DecodeServer — pod IPs are synthetic
    there; a real deployment omits scrape_host)."""
    from ..api import types as t
    from ..obs.appmetrics import scrape_annotations

    dep = t.Deployment()
    dep.metadata.name = name
    dep.metadata.namespace = ns
    dep.spec.replicas = replicas
    dep.spec.selector = t.LabelSelector(match_labels={"app": name})
    dep.spec.template.metadata.labels = {"app": name}
    if scrape_port:
        dep.spec.template.metadata.annotations = scrape_annotations(
            scrape_port, host=scrape_host)
    c = t.Container(
        name="decode", image="ktpu/llama-decode",
        command=["python", "-m", "kubernetes1_tpu.workloads.llama",
                 "--serve"])
    c.resources.requests = {"cpu": cpu}
    dep.spec.template.spec.containers = [c]
    return dep


def _serve_main():
    import time as _time

    srv = DecodeServer().start()
    print(f"decode server on {srv.url}", flush=True)
    try:
        while True:
            _time.sleep(5)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    import sys

    if "--serve" in sys.argv[1:]:
        _serve_main()
    else:
        print("final loss:", train_demo())
