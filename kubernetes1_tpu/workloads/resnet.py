"""ResNet-50 in pure JAX, data-parallel over a single-host mesh
(BASELINE config 3: v5e-4 ResNet-50 Job; north-star metric imgs/sec/chip).

TPU-first choices: NHWC layout (TPU conv native), bfloat16 compute with
float32 params/BN stats, batch sharded over the (dp, fsdp) mesh axes so
XLA reduces gradients over ICI, no pmap (jit + shardings only).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

# (blocks per stage, bottleneck mid-channels) for ResNet-50
STAGES = [(3, 64), (4, 128), (6, 256), (3, 512)]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    width: int = 1  # channel multiplier (tiny configs for tests)
    stages: Tuple[Tuple[int, int], ...] = tuple(STAGES)
    dtype: Any = jnp.bfloat16


def tiny() -> ResNetConfig:
    return ResNetConfig(num_classes=10, width=1, stages=((1, 8), (1, 16)))


def _conv_init(key, kh, kw, cin, cout):
    fan = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * np.sqrt(2.0 / fan)


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def init_params(cfg: ResNetConfig, key: jax.Array) -> Dict[str, Any]:
    keys = iter(jax.random.split(key, 256))
    stem_out = 64 * cfg.width
    params: Dict[str, Any] = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, stem_out), "bn": _bn_init(stem_out)},
        "stages": [],
    }
    cin = stem_out
    for si, (blocks, mid0) in enumerate(cfg.stages):
        mid = mid0 * cfg.width
        cout = mid * 4
        stage: List[Dict[str, Any]] = []
        for bi in range(blocks):
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid), "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid), "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout), "bn3": _bn_init(cout),
            }
            if cin != cout or (bi == 0 and si > 0):
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["proj_bn"] = _bn_init(cout)
            stage.append(blk)
            cin = cout
        params["stages"].append(stage)
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), jnp.float32) / np.sqrt(cin),
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return jax.lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, bn):
    """Training-mode batch norm, HBM-lean: stats accumulate in f32 (one
    fused pass, E[x^2]-E[x]^2 form), but the normalized output stays in the
    compute dtype.  Folding (scale*inv, bias-mean*scale*inv) into two
    per-channel vectors keeps the big-tensor math a single fused
    multiply-add that XLA fuses into the producing conv's epilogue —
    round-tripping activations through f32 here was the #1 HBM cost."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    mean2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + 1e-5) * bn["scale"]
    w = inv.astype(x.dtype)
    b = (bn["bias"] - mean * inv).astype(x.dtype)
    return x * w + b


def forward(cfg: ResNetConfig, params: Dict[str, Any], images: jax.Array) -> jax.Array:
    """images (B, H, W, 3) float -> logits (B, classes) float32."""
    from . import sharding as sh

    x = sh.constrain(images, P(("dp", "fsdp"), None, None, None))
    x = jax.nn.relu(_bn(_conv(x, params["stem"]["conv"], 2, cfg.dtype), params["stem"]["bn"]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            stride = 2 if (bi == 0 and si > 0) else 1
            h = jax.nn.relu(_bn(_conv(x, blk["conv1"], 1, cfg.dtype), blk["bn1"]))
            h = jax.nn.relu(_bn(_conv(h, blk["conv2"], stride, cfg.dtype), blk["bn2"]))
            h = _bn(_conv(h, blk["conv3"], 1, cfg.dtype), blk["bn3"])
            if "proj" in blk:
                x = _bn(_conv(x, blk["proj"], stride, cfg.dtype), blk["proj_bn"])
            x = jax.nn.relu(x + h)
    x = jnp.mean(x, axis=(1, 2))
    return (x @ params["head"]["w"] + params["head"]["b"]).astype(jnp.float32)


def loss_fn(cfg, params, images, labels):
    logits = forward(cfg, params, images)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, labels))


def make_train_step(cfg: ResNetConfig, tx: optax.GradientTransformation):
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(partial(loss_fn, cfg))(params, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def train_demo(cfg: ResNetConfig = None, mesh: Mesh = None, steps: int = 3,
               batch: int = 8, size: int = 32) -> float:
    from . import sharding as sh

    cfg = cfg or tiny()
    mesh = mesh or sh.auto_mesh()
    with sh.use_mesh(mesh):
        params = init_params(cfg, jax.random.key(0))
        tx = optax.sgd(0.1, momentum=0.9)
        opt_state = jax.jit(tx.init)(params)
        step = make_train_step(cfg, tx)
        rng = np.random.default_rng(0)
        # one fixed batch: the demo shows the sharded step memorizing it
        images = jnp.asarray(rng.normal(size=(batch, size, size, 3)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, cfg.num_classes, batch), jnp.int32)
        loss = None
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, images, labels)
        return float(loss)


def bench_imgs_per_sec(batch: int = 64, size: int = 224, steps: int = 10) -> float:
    """imgs/sec on the visible devices (the north-star v5e-4 metric)."""
    import time

    from . import sharding as sh

    cfg = ResNetConfig()
    mesh = sh.auto_mesh()
    with sh.use_mesh(mesh):
        params = init_params(cfg, jax.random.key(0))
        tx = optax.sgd(0.1, momentum=0.9)
        opt_state = jax.jit(tx.init)(params)
        step = make_train_step(cfg, tx)
        rng = np.random.default_rng(0)
        images = jnp.asarray(rng.normal(size=(batch, size, size, 3)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, cfg.num_classes, batch), jnp.int32)
        params, opt_state, loss = step(params, opt_state, images, labels)  # compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, images, labels)
        jax.block_until_ready(loss)
        return batch * steps / (time.perf_counter() - t0)


if __name__ == "__main__":
    print("final loss:", train_demo())
