"""Bench payload self-defense + per-op profiling.

VERDICT r4 Weak #1: a wedged chip claim (the axon tunnel hanging inside
`jax.devices()`) cost the round its flagship number — the payload hung
900 s, the bench tore down without reaping it, and the leaked process
kept the chip unclaimable for hours.  Payloads now guard themselves:

- device_acquisition_watchdog: a TIMER THREAD (not SIGALRM — the hang
  sits inside a C call where Python signal handlers cannot run, but the
  call releases the GIL so another thread still can; verified on this
  box: SIGALRM never fired during a wedged claim, a thread does) that
  writes a distinct `"error": "device acquisition timeout"` result and
  hard-exits long before the bench's outer deadline.

- collect_profile: one profiled step through jax.profiler.trace +
  xprof's hlo_stats, summarized to the top-N self-time ops and a
  compute-vs-HBM verdict — the evidence behind any "HBM-bound ceiling"
  claim in the bench output (VERDICT r3 ask #5 / r4 Weak #4).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import threading
from typing import Optional


def device_acquisition_watchdog(out_path: str, seconds: float = 180.0):
    """Arm before touching jax.devices(); .cancel() once devices are held.
    On expiry: write the distinct error result and _exit(3)."""

    def boom():
        msg = {"error": "device acquisition timeout",
               "watchdog_seconds": seconds}
        try:
            if out_path:
                with open(out_path, "w") as f:
                    json.dump(msg, f)
        except OSError:
            pass
        sys.stderr.write(json.dumps(msg) + "\n")
        sys.stderr.flush()
        os._exit(3)

    timer = threading.Timer(seconds, boom)
    timer.daemon = True
    timer.start()
    return timer


def collect_profile(run_once, tmpdir: str, top_n: int = 5) -> Optional[dict]:
    """Profile one step invocation; return {"top_ops": [...],
    "bound": "hbm|compute|...", ...} or an {"error": ...} dict.  Never
    raises — profiling must not be able to fail the benchmark."""
    import shutil

    try:
        import jax

        with jax.profiler.trace(tmpdir):
            run_once()
        return _summarize_hlo_stats(tmpdir, top_n)
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        # the summary is in hand: multi-MB xplane traces must not pile up
        # in /tmp across bench rounds on this long-lived box
        shutil.rmtree(tmpdir, ignore_errors=True)


def _session_dirs(tmpdir: str):
    return sorted(glob.glob(os.path.join(tmpdir, "plugins", "profile", "*")))


def _summarize_hlo_stats(tmpdir: str, top_n: int) -> dict:
    from xprof.convert import raw_to_tool_data as rtd

    sessions = _session_dirs(tmpdir)
    if not sessions:
        return {"error": "no profile session captured"}
    xspaces = glob.glob(os.path.join(sessions[-1], "*.xplane.pb"))
    if not xspaces:
        return {"error": "no xplane captured"}
    data, _ = rtd.xspace_to_tool_data(xspaces, "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode(errors="replace")
    table = json.loads(data)
    # gviz table: cols have labels, rows carry per-op stats
    cols = [c.get("label", c.get("id", "")) for c in table.get("cols", [])]

    def col(*names):
        for want in names:
            for i, label in enumerate(cols):
                if want.lower() in str(label).lower():
                    return i
        return None

    i_name = col("hlo op name", "hlo_op_name", "op name")
    i_cat = col("category")
    i_self = col("total self time (us)", "self time")
    i_bound = col("bound by", "bottleneck")
    if i_name is None or i_self is None:
        return {"error": f"unrecognized hlo_stats columns: {cols[:12]}"}
    rows = []
    for r in table.get("rows", []):
        c = r.get("c", [])

        def v(i):
            return c[i].get("v") if i is not None and i < len(c) else None

        try:
            rows.append({
                "op": str(v(i_name))[:96],
                "category": v(i_cat),
                "self_time_us": float(v(i_self) or 0.0),
                "bound_by": v(i_bound),
            })
        except (TypeError, ValueError):
            continue
    rows.sort(key=lambda r: -r["self_time_us"])
    if not rows:
        return {"error": "no device ops in trace "
                         "(host-only platform or empty capture)"}
    total = sum(r["self_time_us"] for r in rows) or 1.0
    top = []
    for r in rows[:top_n]:
        top.append({
            "op": r["op"],
            "category": r["category"],
            "self_time_pct": round(100.0 * r["self_time_us"] / total, 1),
            "bound_by": r["bound_by"],
        })
    # overall verdict: weight each op's bound_by by self time
    by_bound: dict = {}
    for r in rows:
        key = str(r["bound_by"] or "unknown").lower()
        by_bound[key] = by_bound.get(key, 0.0) + r["self_time_us"]
    verdict = max(by_bound, key=by_bound.get) if by_bound else "unknown"
    return {
        "top_ops": top,
        "bound": verdict,
        "bound_breakdown_pct": {
            k: round(100.0 * v / total, 1) for k, v in sorted(
                by_bound.items(), key=lambda kv: -kv[1])},
        "ops_counted": len(rows),
    }
